"""CheckpointCoordinator: epoch generation, ack collection, atomic commit.

One coordinator per running PipeGraph. Triggering is a single integer bump
of ``requested_id``; source replicas poll it on their own threads at tuple
boundaries and inject the ``Barrier`` themselves, so the coordinator never
touches a channel and needs no per-message synchronization. Each worker
acknowledges a checkpoint exactly once, shipping all of its fused
replicas' snapshot blobs; the checkpoint commits (manifest + atomic
rename, ``store.py``) when every worker of the graph has acked. Finalize
listeners run on the acking worker's thread — they must be cheap and
thread-safe (the Kafka source only flips a flag and commits offsets from
its own consume loop).

A checkpoint that can never complete (a source finished before the
barrier, a worker crashed) simply stays uncommitted: restore only ever
sees fully-acked checkpoints, which is the correctness contract.
"""

from __future__ import annotations

import os
import queue
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set

from .delta import env_ckpt_async
from .store import CheckpointStore


def env_ckpt_timeout() -> float:
    """``WF_CKPT_TIMEOUT`` (seconds): how long an epoch may stay pending
    before it is failed with a descriptive error naming the unacked
    workers. 0 / unset = no timeout (the pre-timeout behavior: an epoch
    that can never complete simply stays uncommitted)."""
    try:
        return float(os.environ.get("WF_CKPT_TIMEOUT", "0") or 0)
    except ValueError:
        return 0.0  # malformed knob must not take down the graph


class EpochFailed(Exception):
    """Internal marker: an epoch was failed (timeout); ``wait_committed``
    converts it into the user-facing WindFlowError."""


class CheckpointCoordinator:
    def __init__(self, store: CheckpointStore, graph_name: str = "pipegraph",
                 interval_s: Optional[float] = None) -> None:
        self.store = store
        self.graph_name = graph_name
        self.interval_s = interval_s
        # the epoch counter source replicas poll (reads are a single
        # attribute load — safe without the lock; writes hold it).
        # _alloc_id hands out ids BEFORE they publish, so two concurrent
        # triggers can never share an epoch
        self.requested_id = 0
        self._alloc_id = 0
        # workers expected to ack each checkpoint; set by PipeGraph once
        # the topology is built (0 = not running, acks park as pending)
        self.expected_acks = 0
        self._lock = threading.Lock()
        # serializes blob writes against the commit rename: an ack's
        # pending-check + write must be atomic w.r.t. _finalize renaming
        # the staging dir away, or a late writer (a retiring worker
        # racing the last live ack) loses its temp file mid-write and
        # leaks unmanifested blobs into the committed dir. Ordering:
        # _store_lock outside _lock, never the reverse.
        self._store_lock = threading.Lock()
        self._pending: Dict[int, Dict[str, Any]] = {}
        # workers that exited cleanly, with their final state blobs: a
        # finished worker's state is frozen, so its final snapshot is
        # valid for every later epoch (Flink's finished-task semantics —
        # without this, one short-lived source would forever block
        # checkpoints of a still-running graph)
        self._retired: Dict[str, Dict[Any, Any]] = {}
        self._listeners: List[Callable[[int], None]] = []
        # abort listeners (exactly-once sinks): notified with the epoch
        # id when a pending epoch is failed (WF_CKPT_TIMEOUT) or dropped
        # wholesale (rescale teardown) — the epoch will never finalize,
        # so a transactional sink knows its staged records ride the next
        # committed epoch's watermark instead
        self._abort_listeners: List[Callable[[int], None]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # aggregate stats (PipeGraph.get_stats / the /metrics plane)
        self.completed = 0
        self.last_completed_id = 0
        self.last_duration_s = 0.0
        self.last_bytes = 0
        self.total_bytes = 0
        # epoch timeout (WF_CKPT_TIMEOUT): pending epochs older than this
        # fail loudly instead of hanging trigger_checkpoint()/rescale()
        # forever when a worker never acks
        self.epoch_timeout_s = env_ckpt_timeout()
        self.failed_epochs = 0
        # epochs failed by an OSError while staging blobs (disk full,
        # permission loss): the epoch dies loudly, the worker survives
        self.storage_failures = 0
        self.last_failure: Optional[str] = None
        self._failed: Dict[int, str] = {}  # cid -> failure message
        # wait_committed() sleeps here; notified on finalize and failure
        self._commit_cond = threading.Condition(self._lock)
        # worker roster + diagnostics hook, wired by PipeGraph: names make
        # the timeout error actionable, diagnose() adds Worker_last_error
        # / stall-watchdog state for the unacked workers when available
        self.worker_names: List[str] = []
        self.diagnose: Optional[Callable[[List[str]], str]] = None
        # rescale hold point (windflow_tpu.scaling): when an epoch is
        # triggered with hold=True, every worker parks inside
        # ``checkpoint_now`` right after acking it, so the whole graph
        # quiesces exactly at the aligned barrier. The controller then
        # releases them with a directive: "resume" (rescale aborted) or
        # "abandon" (unwind; the runtime plane is rebuilt)
        self._hold_epoch: Optional[int] = None
        self._hold_evt = threading.Event()
        self._hold_directive = "resume"
        self.parked: Set[str] = set()
        self._commit_acked: Dict[int, Set[str]] = {}  # cid -> acked names
        # async snapshot upload (WF_CKPT_ASYNC): an ack only registers
        # the captured blobs as a PENDING upload handle and returns —
        # the worker's cut pause ends there. A single background
        # uploader serializes + writes off the hot path; the epoch
        # finalizes only when every worker acked AND every upload
        # landed (ent["uploads"] == 0). A crash/OSError mid-upload
        # fails the epoch loudly through the same storage-failure path
        # as a synchronous write — exactly-once epoch-id semantics and
        # the fallback ladder are unchanged.
        self.async_enabled = env_ckpt_async()
        self._upload_q: Optional[queue.Queue] = None
        self._upload_thread: Optional[threading.Thread] = None
        self.async_uploads = 0       # uploads completed (any outcome)
        self.async_pending = 0       # uploads currently in flight
        self.upload_usec_total = 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self.interval_s is None or self.interval_s <= 0 \
                or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"{self.graph_name}/ckpt-coord",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=3)
            self._thread = None
        q = self._upload_q
        if q is not None and self._upload_thread is not None:
            q.put(None)  # sentinel: drain remaining uploads, then exit
            self._upload_thread.join(timeout=5)
            self._upload_thread = None
            self._upload_q = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.check_epoch_timeouts()
            self.trigger()

    # -- triggering --------------------------------------------------------
    def trigger(self, force: bool = False, hold: bool = False
                ) -> Optional[int]:
        """Open a new checkpoint epoch and return its id. Without
        ``force``, declines while an earlier checkpoint is still
        in flight (aligned barriers serialize naturally; overlapping
        epochs would only race each other at the aligners).

        ``hold=True`` marks the epoch as a rescale quiesce point: every
        worker parks in ``park_if_held`` right after acking it, until
        ``release_hold`` hands down a directive."""
        timeout = max(2.0 * (self.interval_s or 0.0), 10.0)
        with self._lock:
            if not force:
                now = time.monotonic()
                for ent in self._pending.values():
                    if now - ent["t0"] < timeout:
                        return None
            self._alloc_id = max(self._alloc_id, self.requested_id) + 1
            cid = self._alloc_id
            self._pending[cid] = {"acked": set(), "bytes": 0,
                                  "t0": time.monotonic()}
            if hold:
                # armed BEFORE the epoch publishes: a source may poll the
                # new requested_id and park before trigger() returns
                self._hold_epoch = cid
                self._hold_directive = "resume"
                self._hold_evt.clear()
                self.parked = set()
        # stage BEFORE publishing the epoch: sources poll requested_id and
        # may ack immediately — clearing crashed-run debris after that
        # would race their blob writes
        with self._store_lock:
            self.store.begin(cid)
        with self._lock:
            if cid > self.requested_id:
                self.requested_id = cid
            retired = list(self._retired.items())
        for wname, blobs in retired:
            self.ack(cid, wname, blobs)
        return cid

    # -- acks --------------------------------------------------------------
    def ack(self, ckpt_id: int, worker_name: str,
            blobs: Dict[Any, Any]) -> int:
        """One worker's snapshot for one checkpoint: ``blobs`` maps
        ``(op_name, replica_idx)`` to the replica's state dict. Returns
        bytes written (0 when the checkpoint is unknown/already
        committed — a late barrier after a commit-by-timeout; also 0 in
        async mode, where the write happens off this thread and the
        bytes land in the epoch's tally when the upload does)."""
        if self.async_enabled:
            return self._ack_async(ckpt_id, worker_name, blobs)
        nbytes = 0
        with self._store_lock:
            with self._lock:
                if ckpt_id not in self._pending:
                    return 0
            try:
                for (op_name, idx), state in blobs.items():
                    nbytes += self.store.write_blob(ckpt_id, op_name, idx,
                                                    state)
            except OSError as e:
                # disk full / write failure while staging: fail the EPOCH
                # loudly, never the worker. Staging debris is pruned so a
                # full disk isn't made worse; the next interval retries a
                # fresh epoch with fresh staging.
                shutil.rmtree(self.store._dirname(ckpt_id, staging=True),
                              ignore_errors=True)
                with self._lock:
                    self._fail_epoch_storage_locked(ckpt_id, worker_name, e)
                self._notify_aborted(ckpt_id)
                return 0
        with self._lock:
            ent = self._pending.get(ckpt_id)
            if ent is None:
                return nbytes
            ent["acked"].add(worker_name)
            ent["bytes"] += nbytes
            done = (self.expected_acks > 0
                    and len(ent["acked"]) >= self.expected_acks
                    and ent.get("uploads", 0) == 0)
        if done:
            self._finalize(ckpt_id)
        return nbytes

    # -- async snapshot upload (WF_CKPT_ASYNC) -----------------------------
    def _ack_async(self, ckpt_id: int, worker_name: str,
                   blobs: Dict[Any, Any]) -> int:
        """Register the captured blobs as a pending upload handle and
        return immediately: the barrier fenced only the state CUT. The
        epoch cannot finalize until this upload lands."""
        from ..monitoring.flightrec import thread_recorder

        with self._lock:
            ent = self._pending.get(ckpt_id)
            if ent is None:
                return 0
            ent["acked"].add(worker_name)
            ent["uploads"] = ent.get("uploads", 0) + 1
            self.async_pending += 1
        self._ensure_uploader()
        # the entry object rides along as an incarnation token: after a
        # crash + in-process restart the same ckpt_id can be re-begun
        # with a FRESH entry, and a stale pre-crash upload must not
        # write into (or fail) the reincarnated epoch
        self._upload_q.put((ckpt_id, worker_name, blobs,
                            thread_recorder(), ent))
        return 0

    def _ensure_uploader(self) -> None:
        with self._lock:
            if self._upload_thread is not None:
                return
            self._upload_q = queue.Queue()
            self._upload_thread = threading.Thread(
                target=self._upload_loop,
                name=f"{self.graph_name}/ckpt-upload", daemon=True)
        self._upload_thread.start()

    def _upload_loop(self) -> None:
        while True:
            item = self._upload_q.get()
            if item is None:
                return
            self._upload_one(*item)

    def _upload_one(self, ckpt_id: int, worker_name: str,
                    blobs: Dict[Any, Any], rec: Any, ent: dict) -> None:
        from ..monitoring.flightrec import rec_evt_safe

        t0 = time.perf_counter()
        nbytes = 0
        failed = None
        try:
            with self._store_lock:
                with self._lock:
                    # identity, not id: a reincarnated epoch (crash +
                    # restart re-begins the same ckpt_id) has a fresh
                    # entry and this upload is abandoned
                    alive = self._pending.get(ckpt_id) is ent
                if alive:
                    for (op_name, idx), state in blobs.items():
                        nbytes += self.store.write_blob(
                            ckpt_id, op_name, idx, state)
        except OSError as e:
            # same loud-epoch-failure contract as a synchronous write:
            # the epoch dies, the worker (long resumed) never notices
            failed = e
            shutil.rmtree(self.store._dirname(ckpt_id, staging=True),
                          ignore_errors=True)
        dur_us = (time.perf_counter() - t0) * 1e6
        done = False
        with self._lock:
            self.async_pending -= 1
            self.async_uploads += 1
            self.upload_usec_total += dur_us
            stale = self._pending.get(ckpt_id) is not ent
            if failed is not None:
                if not stale:
                    self._fail_epoch_storage_locked(ckpt_id, worker_name,
                                                    failed)
            elif not stale:
                ent["uploads"] -= 1
                ent["bytes"] += nbytes
                done = (self.expected_acks > 0
                        and len(ent["acked"]) >= self.expected_acks
                        and ent["uploads"] == 0)
        if failed is not None:
            if not stale:
                self._notify_aborted(ckpt_id)
            return
        if rec is not None:
            # the acking worker's ring, written cross-thread: one racy
            # slot write, tolerated the same way the stall watchdog's is
            rec_evt_safe(rec, "ckpt:upload", dur_us,
                         {"ckpt_id": ckpt_id, "worker": worker_name,
                          "bytes": nbytes})
        if done:
            self._finalize(ckpt_id)

    def retire(self, worker_name: str, blobs: Dict[Any, Any]) -> None:
        """A worker finished cleanly: remember its final blobs and ack
        them into every epoch it had not answered yet (its barrier can no
        longer be in flight — it saw EOS on every channel)."""
        with self._lock:
            self._retired[worker_name] = blobs
            open_cids = [cid for cid, ent in self._pending.items()
                         if worker_name not in ent["acked"]]
        for cid in open_cids:
            self.ack(cid, worker_name, blobs)

    def _finalize(self, ckpt_id: int) -> None:
        with self._lock:
            ent = self._pending.pop(ckpt_id, None)
            if ent is None:
                return  # raced another finalize
            # any older still-open checkpoint can no longer matter: the
            # newer one strictly supersedes it
            for old in [c for c in self._pending if c < ckpt_id]:
                self._pending.pop(old, None)
            listeners = list(self._listeners)
        duration = time.monotonic() - ent["t0"]
        with self._store_lock:
            self.store.commit(ckpt_id, {
                "graph": self.graph_name,
                "created_unix": time.time(),
                "duration_sec": round(duration, 6),
                "n_workers": self.expected_acks,
                "bytes": ent["bytes"],
            })
        with self._lock:
            self.completed += 1
            self.last_completed_id = ckpt_id
            self.last_duration_s = duration
            self.last_bytes = ent["bytes"]
            self.total_bytes += ent["bytes"]
            # the rescale controller needs to know WHO acked a held epoch
            # (parked ∪ retired must cover them before teardown is safe)
            self._commit_acked[ckpt_id] = set(ent["acked"])
            for old in [c for c in self._commit_acked if c < ckpt_id]:
                self._commit_acked.pop(old, None)
            self._commit_cond.notify_all()
        # _finalize runs on the LAST acking worker's thread: its flight
        # ring (when recording) gets the commit marker, closing the
        # barrier_open -> align -> snapshot -> commit timeline
        from ..monitoring.flightrec import thread_recorder
        rec = thread_recorder()
        if rec is not None:
            rec.event("ckpt_commit", duration * 1e6,
                      {"ckpt_id": ckpt_id, "bytes": ent["bytes"]})
        for fn in listeners:
            try:
                fn(ckpt_id)
            except Exception:  # listener bugs must not kill the worker
                pass

    # -- epoch timeout (WF_CKPT_TIMEOUT) -----------------------------------
    def _unacked_of(self, acked: Set[str]) -> List[str]:
        names = self.worker_names or []
        return [n for n in names if n not in acked] \
            or [f"<{self.expected_acks - len(acked)} unnamed worker(s)>"]

    def _fail_epoch_locked(self, cid: int, age_s: float) -> str:
        """Drop a pending epoch and compose the descriptive error (lock
        held). The staging dir stays on disk; store.prune cleans it once
        a newer checkpoint commits. ``diagnose`` (when wired — it only
        reads already-collected stats) appends per-worker evidence:
        ``Worker_last_error`` tracebacks, stall-watchdog flags."""
        ent = self._pending.pop(cid, None)
        acked = ent["acked"] if ent else set()
        unacked = self._unacked_of(acked)
        msg = (f"checkpoint epoch {cid} timed out after {age_s:.1f}s "
               f"(WF_CKPT_TIMEOUT): {len(acked)}/{self.expected_acks} "
               f"workers acked; never acked: {', '.join(unacked)}")
        if self.diagnose is not None:
            try:
                extra = self.diagnose(unacked)
            except Exception:
                extra = ""
            if extra:
                msg += f" — {extra}"
        self._failed[cid] = msg
        for old in [c for c in self._failed if c < cid - 16]:
            self._failed.pop(old, None)
        self.failed_epochs += 1
        self.last_failure = msg
        self._commit_cond.notify_all()
        return msg

    def _fail_epoch_storage_locked(self, cid: int, worker_name: str,
                                   err: OSError) -> str:
        """Drop a pending epoch whose blob staging hit an OSError (lock
        held). Same bookkeeping as the timeout path — the epoch will
        never finalize, abort listeners fire, and restore only ever sees
        fully-committed checkpoints."""
        self._pending.pop(cid, None)
        msg = (f"checkpoint epoch {cid} aborted: storage write failure "
               f"while worker {worker_name!r} staged its snapshot "
               f"({type(err).__name__}: {err}) — staging debris pruned, "
               "next interval retries")
        self._failed[cid] = msg
        for old in [c for c in self._failed if c < cid - 16]:
            self._failed.pop(old, None)
        self.failed_epochs += 1
        self.storage_failures += 1
        self.last_failure = msg
        self._commit_cond.notify_all()
        return msg

    def check_epoch_timeouts(self) -> None:
        """Fail pending epochs older than ``WF_CKPT_TIMEOUT``. Called by
        the interval thread each tick and by ``wait_committed``; a
        no-op when the timeout is unset."""
        t = self.epoch_timeout_s
        if t <= 0:
            return
        with self._lock:
            now = time.monotonic()
            stale = [(cid, now - ent["t0"])
                     for cid, ent in self._pending.items()
                     if now - ent["t0"] >= t]
            for cid, age in stale:
                self._fail_epoch_locked(cid, age)
        for cid, _ in stale:
            self._notify_aborted(cid)

    def wait_committed(self, cid: int, timeout_s: Optional[float] = None
                       ) -> None:
        """Block until epoch ``cid`` commits. Raises ``WindFlowError``
        when the epoch fails (WF_CKPT_TIMEOUT elapsed, or ``timeout_s``
        as an explicit override) naming the workers that never acked."""
        from ..basic import WindFlowError

        t = timeout_s if timeout_s is not None else self.epoch_timeout_s
        deadline = time.monotonic() + t if t and t > 0 else None
        while True:
            timed_out_msg = None
            with self._lock:
                if self.last_completed_id >= cid:
                    return
                if cid in self._failed:
                    raise WindFlowError(self._failed[cid])
                if cid not in self._pending:
                    raise WindFlowError(
                        f"checkpoint epoch {cid} was dropped without "
                        "committing (superseded by a newer checkpoint)")
                if deadline is not None and time.monotonic() >= deadline:
                    timed_out_msg = self._fail_epoch_locked(cid, t)
                else:
                    self._commit_cond.wait(0.05)
            if timed_out_msg is not None:
                self._notify_aborted(cid)
                raise WindFlowError(timed_out_msg)

    # -- rescale hold point (windflow_tpu.scaling) -------------------------
    def park_if_held(self, ckpt_id: int, worker_name: str) -> Optional[str]:
        """Called by every worker right after acking ``ckpt_id``. For a
        held (rescale) epoch the worker blocks here — the graph quiesces
        exactly at the aligned barrier, with every pre-barrier tuple
        already flushed downstream and nothing post-barrier produced —
        until the controller releases it. Returns the release directive
        ("resume" / "abandon"), or None when the epoch is not held."""
        with self._lock:
            if self._hold_epoch != ckpt_id:
                return None
            self.parked.add(worker_name)
            self._commit_cond.notify_all()
            evt = self._hold_evt
        evt.wait()
        with self._lock:
            return self._hold_directive

    def wait_all_parked(self, cid: int, timeout_s: float) -> bool:
        """True once every worker that acked the held epoch ``cid`` live
        (i.e. not via retirement) is parked — the moment teardown/rewire
        is safe. The epoch must already be committed."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                acked = self._commit_acked.get(cid)
                if acked is not None \
                        and acked <= (self.parked | set(self._retired)):
                    return True
                if time.monotonic() >= deadline:
                    return False
                self._commit_cond.wait(0.05)

    def release_hold(self, directive: str = "resume") -> None:
        """Release every parked worker with ``directive``: "resume"
        continues processing as after a normal checkpoint (aborted
        rescale), "abandon" unwinds the worker silently (the runtime
        plane is being rebuilt)."""
        with self._lock:
            self._hold_directive = directive
            self._hold_epoch = None
            evt = self._hold_evt
        evt.set()

    def abort_pending(self) -> None:
        """Drop every still-pending epoch (rescale teardown: epochs
        opened against the old runtime plane can never complete once its
        workers are gone)."""
        with self._lock:
            dropped = list(self._pending)
            self._pending.clear()
            self._retired.clear()
            self._commit_cond.notify_all()
        for cid in dropped:
            self._notify_aborted(cid)

    def _notify_aborted(self, cid: int) -> None:
        for fn in list(self._abort_listeners):
            try:
                fn(cid)
            except Exception:
                pass  # listener bugs must not kill the coordinator

    # -- listeners ---------------------------------------------------------
    def add_finalize_listener(self, fn: Callable[[int], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def add_abort_listener(self, fn: Callable[[int], None]) -> None:
        with self._lock:
            self._abort_listeners.append(fn)

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "Checkpoints_completed": self.completed,
                "Checkpoints_requested": self.requested_id,
                "Checkpoint_last_id": self.last_completed_id,
                "Checkpoint_last_duration_sec": round(self.last_duration_s,
                                                      6),
                "Checkpoint_last_bytes": self.last_bytes,
                "Checkpoint_bytes_total": self.total_bytes,
                "Checkpoint_store_dir": self.store.root,
                "Checkpoint_failed_epochs": self.failed_epochs,
                "Checkpoint_failures": self.failed_epochs,
                "Checkpoint_storage_failures": self.storage_failures,
                "Checkpoint_verify_failures": self.store.verify_failures,
                "Checkpoint_last_failure": self.last_failure,
                # incremental/async plane (WF_CKPT_DELTA / WF_CKPT_ASYNC)
                "Checkpoint_delta_blobs": self.store.delta_blobs,
                "Checkpoint_delta_bytes": self.store.delta_bytes,
                "Checkpoint_full_bytes": self.store.full_bytes,
                "Checkpoint_async_pending": self.async_pending,
                "Checkpoint_async_uploads": self.async_uploads,
                "Checkpoint_upload_usec_total": round(
                    self.upload_usec_total, 1),
            }
