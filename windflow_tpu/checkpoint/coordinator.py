"""CheckpointCoordinator: epoch generation, ack collection, atomic commit.

One coordinator per running PipeGraph. Triggering is a single integer bump
of ``requested_id``; source replicas poll it on their own threads at tuple
boundaries and inject the ``Barrier`` themselves, so the coordinator never
touches a channel and needs no per-message synchronization. Each worker
acknowledges a checkpoint exactly once, shipping all of its fused
replicas' snapshot blobs; the checkpoint commits (manifest + atomic
rename, ``store.py``) when every worker of the graph has acked. Finalize
listeners run on the acking worker's thread — they must be cheap and
thread-safe (the Kafka source only flips a flag and commits offsets from
its own consume loop).

A checkpoint that can never complete (a source finished before the
barrier, a worker crashed) simply stays uncommitted: restore only ever
sees fully-acked checkpoints, which is the correctness contract.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .store import CheckpointStore


class CheckpointCoordinator:
    def __init__(self, store: CheckpointStore, graph_name: str = "pipegraph",
                 interval_s: Optional[float] = None) -> None:
        self.store = store
        self.graph_name = graph_name
        self.interval_s = interval_s
        # the epoch counter source replicas poll (reads are a single
        # attribute load — safe without the lock; writes hold it).
        # _alloc_id hands out ids BEFORE they publish, so two concurrent
        # triggers can never share an epoch
        self.requested_id = 0
        self._alloc_id = 0
        # workers expected to ack each checkpoint; set by PipeGraph once
        # the topology is built (0 = not running, acks park as pending)
        self.expected_acks = 0
        self._lock = threading.Lock()
        # serializes blob writes against the commit rename: an ack's
        # pending-check + write must be atomic w.r.t. _finalize renaming
        # the staging dir away, or a late writer (a retiring worker
        # racing the last live ack) loses its temp file mid-write and
        # leaks unmanifested blobs into the committed dir. Ordering:
        # _store_lock outside _lock, never the reverse.
        self._store_lock = threading.Lock()
        self._pending: Dict[int, Dict[str, Any]] = {}
        # workers that exited cleanly, with their final state blobs: a
        # finished worker's state is frozen, so its final snapshot is
        # valid for every later epoch (Flink's finished-task semantics —
        # without this, one short-lived source would forever block
        # checkpoints of a still-running graph)
        self._retired: Dict[str, Dict[Any, Any]] = {}
        self._listeners: List[Callable[[int], None]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # aggregate stats (PipeGraph.get_stats / the /metrics plane)
        self.completed = 0
        self.last_completed_id = 0
        self.last_duration_s = 0.0
        self.last_bytes = 0
        self.total_bytes = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self.interval_s is None or self.interval_s <= 0 \
                or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"{self.graph_name}/ckpt-coord",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=3)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.trigger()

    # -- triggering --------------------------------------------------------
    def trigger(self, force: bool = False) -> Optional[int]:
        """Open a new checkpoint epoch and return its id. Without
        ``force``, declines while an earlier checkpoint is still
        in flight (aligned barriers serialize naturally; overlapping
        epochs would only race each other at the aligners)."""
        timeout = max(2.0 * (self.interval_s or 0.0), 10.0)
        with self._lock:
            if not force:
                now = time.monotonic()
                for ent in self._pending.values():
                    if now - ent["t0"] < timeout:
                        return None
            self._alloc_id = max(self._alloc_id, self.requested_id) + 1
            cid = self._alloc_id
            self._pending[cid] = {"acked": set(), "bytes": 0,
                                  "t0": time.monotonic()}
        # stage BEFORE publishing the epoch: sources poll requested_id and
        # may ack immediately — clearing crashed-run debris after that
        # would race their blob writes
        with self._store_lock:
            self.store.begin(cid)
        with self._lock:
            if cid > self.requested_id:
                self.requested_id = cid
            retired = list(self._retired.items())
        for wname, blobs in retired:
            self.ack(cid, wname, blobs)
        return cid

    # -- acks --------------------------------------------------------------
    def ack(self, ckpt_id: int, worker_name: str,
            blobs: Dict[Any, Any]) -> int:
        """One worker's snapshot for one checkpoint: ``blobs`` maps
        ``(op_name, replica_idx)`` to the replica's state dict. Returns
        bytes written (0 when the checkpoint is unknown/already
        committed — a late barrier after a commit-by-timeout)."""
        nbytes = 0
        with self._store_lock:
            with self._lock:
                if ckpt_id not in self._pending:
                    return 0
            for (op_name, idx), state in blobs.items():
                nbytes += self.store.write_blob(ckpt_id, op_name, idx,
                                                state)
        with self._lock:
            ent = self._pending.get(ckpt_id)
            if ent is None:
                return nbytes
            ent["acked"].add(worker_name)
            ent["bytes"] += nbytes
            done = (self.expected_acks > 0
                    and len(ent["acked"]) >= self.expected_acks)
        if done:
            self._finalize(ckpt_id)
        return nbytes

    def retire(self, worker_name: str, blobs: Dict[Any, Any]) -> None:
        """A worker finished cleanly: remember its final blobs and ack
        them into every epoch it had not answered yet (its barrier can no
        longer be in flight — it saw EOS on every channel)."""
        with self._lock:
            self._retired[worker_name] = blobs
            open_cids = [cid for cid, ent in self._pending.items()
                         if worker_name not in ent["acked"]]
        for cid in open_cids:
            self.ack(cid, worker_name, blobs)

    def _finalize(self, ckpt_id: int) -> None:
        with self._lock:
            ent = self._pending.pop(ckpt_id, None)
            if ent is None:
                return  # raced another finalize
            # any older still-open checkpoint can no longer matter: the
            # newer one strictly supersedes it
            for old in [c for c in self._pending if c < ckpt_id]:
                self._pending.pop(old, None)
            listeners = list(self._listeners)
        duration = time.monotonic() - ent["t0"]
        with self._store_lock:
            self.store.commit(ckpt_id, {
                "graph": self.graph_name,
                "created_unix": time.time(),
                "duration_sec": round(duration, 6),
                "n_workers": self.expected_acks,
                "bytes": ent["bytes"],
            })
        with self._lock:
            self.completed += 1
            self.last_completed_id = ckpt_id
            self.last_duration_s = duration
            self.last_bytes = ent["bytes"]
            self.total_bytes += ent["bytes"]
        # _finalize runs on the LAST acking worker's thread: its flight
        # ring (when recording) gets the commit marker, closing the
        # barrier_open -> align -> snapshot -> commit timeline
        from ..monitoring.flightrec import thread_recorder
        rec = thread_recorder()
        if rec is not None:
            rec.event("ckpt_commit", duration * 1e6,
                      {"ckpt_id": ckpt_id, "bytes": ent["bytes"]})
        for fn in listeners:
            try:
                fn(ckpt_id)
            except Exception:  # listener bugs must not kill the worker
                pass

    # -- listeners ---------------------------------------------------------
    def add_finalize_listener(self, fn: Callable[[int], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "Checkpoints_completed": self.completed,
                "Checkpoints_requested": self.requested_id,
                "Checkpoint_last_id": self.last_completed_id,
                "Checkpoint_last_duration_sec": round(self.last_duration_s,
                                                      6),
                "Checkpoint_last_bytes": self.last_bytes,
                "Checkpoint_bytes_total": self.total_bytes,
                "Checkpoint_store_dir": self.store.root,
            }
