"""CheckpointStore: the versioned on-disk layout of aligned snapshots.

Layout under one root directory::

    <root>/
      ckpt_0000000003.inprogress/     # staging: blobs land here first
        reduce_1a2b3c4d__0.blob
        source_5e6f7a8b__0.blob
      ckpt_0000000002/                # committed: manifest present
        manifest.json
        *.blob

Every write is crash-safe by construction: blobs and the manifest are
written to a ``.tmp`` sibling and published with ``os.replace`` (atomic
rename on POSIX), and a checkpoint becomes visible as a whole only when
its staging directory is atomically renamed to the final name. A crash at
any point leaves either the previous committed checkpoint intact or a
``.inprogress`` directory that restore ignores. Retention keeps the last
``retain`` committed checkpoints.

Blob files are named ``<sanitized-op-name>_<crc32>__<replica>.blob`` (the
crc disambiguates op names that sanitize identically — it says nothing
about the blob's CONTENT); each blob pickles ``{"op": <exact name>,
"replica": idx, "state": <replica state dict>}`` so restore matches
replicas by exact name, never by file name.

Content integrity (``WF_CKPT_VERIFY``, on by default): every blob's
sha256 digest is recorded in the manifest at snapshot time, and restore
re-hashes each blob before unpickling it — a torn, truncated, or
bit-flipped blob raises a typed ``CorruptCheckpointError`` naming the
bad file instead of feeding garbage state into the graph. Manifests
written before this scheme carry no ``digests`` map and restore with a
warning, never an error.

Incremental checkpoints (``WF_CKPT_DELTA``, off by default) add two
manifest maps — the on-disk layout stays readable by pre-delta restores
of non-delta epochs, and pre-delta manifests keep restoring unchanged:

- ``refs: {fname: ancestor_ckpt_id}`` — this epoch's blob is
  byte-identical to the named committed ancestor's (same payload
  digest), so the file is *referenced*, not rewritten. Refs always
  point at the directory PHYSICALLY holding the bytes (one hop, never
  ref-of-ref): ``write_blob`` resolves through the previous manifest's
  own refs before recording.
- ``deps: {fname: [base_ckpt_ids]}`` — this epoch's blob is a *state
  delta* (dirty slot rows / a cold-tier WAL) patching the named base
  epochs' same-name blob. ``load_states`` loads the base state(s) and
  materializes the full state before returning, so every restore
  consumer (supervisor ladder, repartitioner, ``restore_from=``) still
  sees full states.

``verify()`` hashes the transitive closure (refs ∪ deps), so a corrupt
ancestor flags every dependent epoch; ``prune`` keeps the closure of
the retained epochs alive — a blob is never deleted while any newer
manifest still references or depends on its directory.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import shutil
import threading
import warnings
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..basic import WindFlowError
from . import delta as _delta

MANIFEST = "manifest.json"
FORMAT_VERSION = 1

_CKPT_RE = re.compile(r"^ckpt_(\d{10})$")


class CorruptCheckpointError(WindFlowError):
    """A checkpoint failed content verification: a blob's sha256 digest
    does not match the manifest, a manifested blob is missing, the
    manifest itself is undecodable, or a blob cannot be unpickled. The
    message names the bad file. The supervisor's fallback ladder catches
    this and walks to the next-older checkpoint."""


def env_ckpt_verify() -> bool:
    """``WF_CKPT_VERIFY``: write blob digests into manifests and verify
    them on restore. On by default; 0/false/off disables both sides
    (the microbench A/B knob — and an escape hatch, not a config)."""
    v = os.environ.get("WF_CKPT_VERIFY", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def _hash_bytes(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


def _hash_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return "sha256:" + h.hexdigest()


def blob_name(op_name: str, replica_idx: int) -> str:
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in op_name)
    crc = zlib.crc32(op_name.encode("utf-8", "surrogatepass")) & 0xFFFFFFFF
    return f"{safe}_{crc:08x}__{replica_idx}.blob"


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class CheckpointStore:
    # per-root store locks (process-wide): retain-K prune and a
    # concurrent restore's blob reads of the SAME root serialize here,
    # so prune can never delete a checkpoint mid-read — two graph
    # instances (a live coordinator committing, another restoring) may
    # share one root without coordinating
    _root_locks: Dict[str, threading.RLock] = {}
    _root_guard = threading.Lock()

    @classmethod
    def _lock_of(cls, root: str) -> threading.RLock:
        key = os.path.abspath(root)
        with cls._root_guard:
            lock = cls._root_locks.get(key)
            if lock is None:
                lock = cls._root_locks[key] = threading.RLock()
            return lock

    def __init__(self, root: str, retain: int = 3) -> None:
        self.root = root
        self.retain = max(1, int(retain))
        os.makedirs(root, exist_ok=True)
        # digests of staged blobs, keyed ckpt_id -> {fname: "sha256:..."}
        # — hashed from the in-memory payload at write time (free second
        # read avoided); commit() folds them into the manifest
        self._digests: Dict[int, Dict[str, str]] = {}
        self._digest_lock = threading.Lock()
        # cumulative digest-verification failures observed by THIS store
        # instance (surfaced as Checkpoint_verify_failures /
        # windflow_ckpt_verify_failures_total)
        self.verify_failures = 0
        # incremental-checkpoint staging state (WF_CKPT_DELTA): per-epoch
        # blob refs (fname -> ancestor cid physically holding identical
        # bytes) and state-delta deps (fname -> base cids the state
        # patches), folded into the manifest at commit
        self._refs: Dict[int, Dict[str, int]] = {}
        self._deps: Dict[int, Dict[str, List[int]]] = {}
        self._ref_base: Dict[int, Optional[int]] = {}
        self._manifest_cache: Dict[int, Dict[str, Any]] = {}
        # cumulative incremental-checkpoint counters (this instance):
        # blobs not written in full form (ref'd or delta-form), the
        # physical bytes those cost, and the physical bytes of full blobs
        self.delta_blobs = 0
        self.delta_bytes = 0
        self.full_bytes = 0

    # -- paths -------------------------------------------------------------
    def _dirname(self, ckpt_id: int, staging: bool = False) -> str:
        d = os.path.join(self.root, f"ckpt_{ckpt_id:010d}")
        return d + ".inprogress" if staging else d

    def begin(self, ckpt_id: int) -> None:
        """Start (or restart) staging for a checkpoint: stale debris from
        a crashed attempt at the same id must not leak into the manifest."""
        staging = self._dirname(ckpt_id, staging=True)
        shutil.rmtree(staging, ignore_errors=True)
        os.makedirs(staging, exist_ok=True)
        with self._digest_lock:
            self._digests.pop(ckpt_id, None)
            self._refs.pop(ckpt_id, None)
            self._deps.pop(ckpt_id, None)
            # the dedup base for this epoch's blobs: the latest epoch
            # COMMITTED when staging opened (one listdir per epoch)
            self._ref_base[ckpt_id] = (
                self.latest() if _delta.env_ckpt_delta() else None)

    def _committed_manifest(self, cid: int) -> Optional[Dict[str, Any]]:
        """Manifest of a committed epoch, cached (committed manifests are
        immutable; pruned entries are evicted by ``prune``)."""
        with self._digest_lock:
            m = self._manifest_cache.get(cid)
        if m is not None:
            return m
        try:
            m = self.load_manifest(self._dirname(cid))
        except (FileNotFoundError, CorruptCheckpointError):
            return None
        with self._digest_lock:
            self._manifest_cache[cid] = m
        return m

    # -- writes ------------------------------------------------------------
    def write_blob(self, ckpt_id: int, op_name: str, replica_idx: int,
                   state: Any) -> int:
        """Pickle one replica's snapshot into the staging dir (atomic
        tmp+rename). Returns the logical byte size of the snapshot.

        With ``WF_CKPT_DELTA`` on (and digests available), a payload
        whose digest matches the previous committed epoch's same-name
        blob is recorded as a manifest *ref* instead of rewritten —
        zero physical bytes for an unchanged shard."""
        staging = self._dirname(ckpt_id, staging=True)
        os.makedirs(staging, exist_ok=True)
        payload = pickle.dumps(
            {"op": op_name, "replica": replica_idx, "state": state},
            protocol=pickle.HIGHEST_PROTOCOL)
        fname = blob_name(op_name, replica_idx)
        digest = None
        if env_ckpt_verify():
            digest = _hash_bytes(payload)
            with self._digest_lock:
                self._digests.setdefault(ckpt_id, {})[fname] = digest
        bases = _delta.delta_bases(state)
        with self._digest_lock:
            if bases:
                self._deps.setdefault(ckpt_id, {})[fname] = sorted(
                    int(b) for b in bases)
            else:
                self._deps.get(ckpt_id, {}).pop(fname, None)
        if digest is not None and _delta.env_ckpt_delta():
            base_cid = self._ref_base.get(ckpt_id)
            if base_cid is not None:
                bman = self._committed_manifest(base_cid)
                if bman is not None and \
                        (bman.get("digests") or {}).get(fname) == digest:
                    # identical bytes already on disk: resolve through
                    # the base's own refs so our ref points one hop at
                    # the directory physically holding the blob
                    phys = int((bman.get("refs") or {}).get(fname, base_cid))
                    with self._digest_lock:
                        self._refs.setdefault(ckpt_id, {})[fname] = phys
                        self.delta_blobs += 1
                    return len(payload)
        with self._digest_lock:
            self._refs.get(ckpt_id, {}).pop(fname, None)
            if bases:
                self.delta_blobs += 1
                self.delta_bytes += len(payload)
            else:
                self.full_bytes += len(payload)
        _atomic_write(os.path.join(staging, fname), payload)
        return len(payload)

    def staged_blobs(self, ckpt_id: int) -> List[str]:
        staging = self._dirname(ckpt_id, staging=True)
        try:
            return sorted(f for f in os.listdir(staging)
                          if f.endswith(".blob"))
        except FileNotFoundError:
            return []

    def commit(self, ckpt_id: int, manifest: Dict[str, Any]) -> str:
        """Finalize: manifest into staging, then one atomic directory
        rename makes the whole checkpoint visible. Prunes old ones."""
        staging = self._dirname(ckpt_id, staging=True)
        final = self._dirname(ckpt_id)
        manifest = dict(manifest)
        manifest.setdefault("format", FORMAT_VERSION)
        manifest["ckpt_id"] = ckpt_id
        with self._digest_lock:
            cached = self._digests.pop(ckpt_id, {})
            refs = dict(self._refs.pop(ckpt_id, {}))
            deps = dict(self._deps.pop(ckpt_id, {}))
            self._ref_base.pop(ckpt_id, None)
        staged = self.staged_blobs(ckpt_id)
        # a blob both staged and ref'd (re-written within one epoch)
        # carries identical bytes either way — prefer the local file
        refs = {f: c for f, c in refs.items() if f not in staged}
        manifest["blobs"] = sorted(set(staged) | set(refs))
        if refs:
            manifest["refs"] = {f: int(c) for f, c in sorted(refs.items())}
        if deps:
            manifest["deps"] = {f: [int(x) for x in b]
                                for f, b in sorted(deps.items())}
        if env_ckpt_verify():
            # blobs written through another store instance (or with the
            # knob off at write time) aren't in the cache: hash the file
            # (ref'd blobs are always cached — a ref requires the digest)
            manifest["digests"] = {
                fname: cached.get(fname)
                or _hash_file(os.path.join(staging, fname))
                for fname in manifest["blobs"]}
        _atomic_write(os.path.join(staging, MANIFEST),
                      json.dumps(manifest, indent=1).encode())
        shutil.rmtree(final, ignore_errors=True)  # same-id re-commit
        os.replace(staging, final)
        with self._digest_lock:
            self._manifest_cache[ckpt_id] = manifest
        self.prune()
        return final

    def prune(self) -> None:
        # the whole sweep holds the per-root store lock: a concurrent
        # restore_from= reading this root (load_states below) holds the
        # same lock for its whole blob read, so retention can never
        # delete a checkpoint out from under it mid-read
        with self._lock_of(self.root):
            done = self.completed_ids()
            # retention keeps the last `retain` epochs PLUS the closure
            # of every epoch they reference or depend on: a delta chain's
            # ancestor blob is never dropped while a retained manifest
            # still resolves into it (the ref-count fix for delta chains)
            keep = set(done[-self.retain:])
            frontier = list(keep)
            while frontier:
                m = self._committed_manifest(frontier.pop())
                if m is None:
                    continue
                targets = {int(c) for c in (m.get("refs") or {}).values()}
                for bases in (m.get("deps") or {}).values():
                    targets.update(int(b) for b in bases)
                for t in targets:
                    if t not in keep:
                        keep.add(t)
                        frontier.append(t)
            for cid in done:
                if cid not in keep:
                    shutil.rmtree(self._dirname(cid), ignore_errors=True)
                    with self._digest_lock:
                        self._manifest_cache.pop(cid, None)
            # staging debris older than the newest committed checkpoint
            # can never complete (its coordinator is gone) — clean it up
            if done:
                for name in os.listdir(self.root):
                    if name.endswith(".inprogress"):
                        m = _CKPT_RE.match(name[:-len(".inprogress")])
                        if m and int(m.group(1)) <= done[-1]:
                            shutil.rmtree(os.path.join(self.root, name),
                                          ignore_errors=True)

    # -- reads -------------------------------------------------------------
    def completed_ids(self) -> List[int]:
        out = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        for name in names:
            m = _CKPT_RE.match(name)
            if m and os.path.exists(os.path.join(self.root, name, MANIFEST)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self) -> Optional[int]:
        ids = self.completed_ids()
        return ids[-1] if ids else None

    def checkpoint_dir(self, ckpt_id: int) -> Optional[str]:
        """Directory holding a checkpoint's blobs: the committed dir when
        present, else the staging dir (diagnostics/tests only — restore
        goes through ``resolve`` and accepts committed checkpoints only)."""
        final = self._dirname(ckpt_id)
        if os.path.isdir(final):
            return final
        staging = self._dirname(ckpt_id, staging=True)
        return staging if os.path.isdir(staging) else None

    @staticmethod
    def load_manifest(ckpt_dir: str) -> Dict[str, Any]:
        path = os.path.join(ckpt_dir, MANIFEST)
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            raise
        except (ValueError, UnicodeDecodeError, OSError) as e:
            # a torn/garbled manifest is corruption, not a crash: typed
            # so the supervisor's fallback ladder can walk past it
            raise CorruptCheckpointError(
                f"checkpoint manifest {path}: undecodable "
                f"({type(e).__name__}: {e})") from e

    @staticmethod
    def load_blob(ckpt_dir: str, fname: str) -> Dict[str, Any]:
        with open(os.path.join(ckpt_dir, fname), "rb") as f:
            return pickle.load(f)

    @classmethod
    def resolve(cls, path: str) -> Tuple[int, str, Dict[str, Any]]:
        """Resolve a restore target: either one checkpoint directory (has
        a manifest) or a store root (picks the latest committed
        checkpoint). Returns ``(ckpt_id, dir, manifest)``."""
        from ..basic import WindFlowError

        if os.path.exists(os.path.join(path, MANIFEST)):
            manifest = cls.load_manifest(path)
            return int(manifest["ckpt_id"]), path, manifest
        store = cls(path)
        cid = store.latest()
        if cid is None:
            raise WindFlowError(
                f"restore_from={path!r}: no committed checkpoint found "
                "(expected a checkpoint directory with a manifest.json or "
                "a store root containing ckpt_* directories)")
        d = store._dirname(cid)
        return cid, d, cls.load_manifest(d)

    def load_states(self, ckpt_dir: str, manifest: Dict[str, Any]
                    ) -> Dict[Tuple[str, int], Any]:
        """All replica states of one checkpoint, keyed (op name, idx).
        The whole read holds the checkpoint root's store lock, excluding
        a concurrent ``prune`` (a live coordinator committing into the
        same root) for the duration — the blobs named by the manifest
        cannot vanish halfway through the restore.

        With ``WF_CKPT_VERIFY`` on (default), each blob is re-hashed
        against the manifest's digest BEFORE unpickling; any mismatch,
        missing blob, or undecodable pickle raises
        ``CorruptCheckpointError`` naming the bad file. Pre-digest
        manifests (no ``digests`` map) restore with a warning.

        Incremental epochs restore transparently: ref'd blobs are read
        from the ancestor directory physically holding them, and
        delta-form states are materialized against their base epoch's
        blob — the caller always receives FULL states. A missing or
        corrupt ancestor anywhere in the chain raises
        ``CorruptCheckpointError`` (the ladder then walks past every
        epoch depending on it)."""
        verify = env_ckpt_verify()
        digests = manifest.get("digests") or {}
        blobs = manifest.get("blobs", [])
        if verify and blobs and not digests:
            warnings.warn(
                f"checkpoint {ckpt_dir} carries no content digests "
                "(written before integrity verification, or with "
                "WF_CKPT_VERIFY=0): restoring unverified",
                RuntimeWarning, stacklevel=2)
        root = os.path.dirname(os.path.abspath(ckpt_dir)) or self.root
        out: Dict[Tuple[str, int], Any] = {}
        with self._lock_of(root):
            for fname in blobs:
                state, op, rep = self._load_state_chain(
                    root, ckpt_dir, manifest, fname, verify)
                out[(op, rep)] = state
        return out

    def _read_blob_checked(self, blob_dir: str, fname: str,
                           want: Optional[str]) -> Dict[str, Any]:
        path = os.path.join(blob_dir, fname)
        if want is not None:
            try:
                got = _hash_file(path)
            except OSError as e:
                self.verify_failures += 1
                raise CorruptCheckpointError(
                    f"checkpoint blob {path}: unreadable "
                    f"({type(e).__name__}: {e})") from e
            if got != want:
                self.verify_failures += 1
                raise CorruptCheckpointError(
                    f"checkpoint blob {path}: content digest "
                    f"mismatch (manifest {want}, file {got}) — "
                    "the blob is torn or corrupted on disk")
        try:
            return self.load_blob(blob_dir, fname)
        except CorruptCheckpointError:
            raise
        except Exception as e:
            # digest matched (or verification off) yet the pickle
            # is undecodable / the file vanished: still corruption
            self.verify_failures += 1
            raise CorruptCheckpointError(
                f"checkpoint blob {path}: undecodable "
                f"({type(e).__name__}: {e})") from e

    def _load_state_chain(self, root: str, ckpt_dir: str,
                          manifest: Dict[str, Any], fname: str,
                          verify: bool) -> Tuple[Any, str, int]:
        """One blob's FULL state: read from its physical location (own
        dir or the ref'd ancestor's), then materialize delta form
        against the base epoch's same-name blob (recursive — engine
        chains are one hop deep, base is always a full snapshot)."""
        digests = manifest.get("digests") or {}
        refs = manifest.get("refs") or {}
        blob_dir = ckpt_dir
        if fname in refs:
            blob_dir = os.path.join(root, f"ckpt_{int(refs[fname]):010d}")
        blob = self._read_blob_checked(
            blob_dir, fname, digests.get(fname) if verify else None)
        state = blob["state"]
        bases = _delta.delta_bases(state)
        if bases:
            base_states: Dict[int, Any] = {}
            for bcid in sorted(bases):
                bdir = os.path.join(root, f"ckpt_{int(bcid):010d}")
                try:
                    bman = self.load_manifest(bdir)
                except FileNotFoundError as e:
                    self.verify_failures += 1
                    raise CorruptCheckpointError(
                        f"checkpoint blob {os.path.join(ckpt_dir, fname)}: "
                        f"state delta references epoch {bcid}, whose "
                        "manifest is missing (ancestor pruned or lost) — "
                        "the delta chain cannot be materialized") from e
                bstate, _, _ = self._load_state_chain(
                    root, bdir, bman, fname, verify)
                base_states[bcid] = bstate
            try:
                state = _delta.materialize(state, base_states)
            except CorruptCheckpointError:
                raise
            except Exception as e:
                self.verify_failures += 1
                raise CorruptCheckpointError(
                    f"checkpoint blob {os.path.join(ckpt_dir, fname)}: "
                    f"delta materialization failed "
                    f"({type(e).__name__}: {e})") from e
        return state, blob["op"], int(blob["replica"])

    # -- integrity ---------------------------------------------------------
    def verify(self, ckpt_id: Optional[int] = None) -> Dict[int, Dict[str, Any]]:
        """Offline integrity sweep: re-hash every blob of one (or every)
        committed checkpoint against its manifest, WITHOUT unpickling
        anything. Returns ``{ckpt_id: {"ok", "problems", "blobs",
        "bytes", "digested"}}`` — never raises on corruption, so an
        operator can survey a damaged store in one call.

        Incremental epochs are checked over their TRANSITIVE closure:
        a ref'd blob is hashed at its physical ancestor location, and a
        delta blob's base epoch is verified for the same blob name — a
        single corrupt ancestor therefore flags every epoch whose chain
        passes through it."""
        ids = [ckpt_id] if ckpt_id is not None else self.completed_ids()
        report: Dict[int, Dict[str, Any]] = {}
        memo: Dict[Tuple[int, str], List[str]] = {}
        manifests: Dict[int, Any] = {}
        with self._lock_of(self.root):
            for cid in ids:
                problems: List[str] = []
                nbytes = 0
                manifest = self._verify_manifest_of(cid, manifests)
                if isinstance(manifest, str):  # load error message
                    report[cid] = {"ok": False, "problems": [manifest],
                                   "blobs": 0, "bytes": 0,
                                   "digested": False}
                    continue
                digested = bool(manifest.get("digests"))
                for fname in manifest.get("blobs", []):
                    probs, size = self._verify_blob_closure(
                        cid, fname, memo, manifests)
                    problems.extend(probs)
                    nbytes += size
                report[cid] = {"ok": not problems, "problems": problems,
                               "blobs": len(manifest.get("blobs", [])),
                               "bytes": nbytes, "digested": digested}
        return report

    def _verify_manifest_of(self, cid: int, manifests: Dict[int, Any]):
        """Manifest or an error STRING (memoized per verify sweep)."""
        if cid not in manifests:
            try:
                manifests[cid] = self.load_manifest(self._dirname(cid))
            except (FileNotFoundError, CorruptCheckpointError) as e:
                manifests[cid] = str(e)
        return manifests[cid]

    def _verify_blob_closure(self, cid: int, fname: str,
                             memo: Dict[Tuple[int, str], List[str]],
                             manifests: Dict[int, Any]
                             ) -> Tuple[List[str], int]:
        """Problems for one blob AND everything it transitively refs or
        deps on; ``memo`` keeps shared ancestors hashed once per sweep.
        Returns (problems, physical bytes of this blob)."""
        key = (cid, fname)
        if key in memo:
            return memo[key], 0
        memo[key] = probs = []  # pre-seed: a cycle (impossible) ends
        manifest = self._verify_manifest_of(cid, manifests)
        if isinstance(manifest, str):
            probs.append(f"{fname}: epoch {cid}: {manifest}")
            return probs, 0
        refs = manifest.get("refs") or {}
        phys_cid = int(refs.get(fname, cid))
        path = os.path.join(self._dirname(phys_cid), fname)
        nbytes = 0
        try:
            nbytes = os.path.getsize(path)
            got = _hash_file(path)
        except OSError as e:
            probs.append(f"{fname}: unreadable ({type(e).__name__}: {e})")
            got = None
        want = (manifest.get("digests") or {}).get(fname)
        if want is not None and got is not None and got != want:
            probs.append(f"{fname}: digest mismatch "
                         f"(manifest {want}, file {got})")
        for bcid in (manifest.get("deps") or {}).get(fname, []):
            sub, _ = self._verify_blob_closure(int(bcid), fname,
                                               memo, manifests)
            for p in sub:
                probs.append(f"{fname}: delta base epoch {bcid}: {p}"
                             if not p.startswith(fname) else
                             f"{fname}: delta base epoch {bcid}: "
                             + p[len(fname) + 2:])
        return probs, nbytes

    def quarantine(self, ckpt_id: int) -> Optional[str]:
        """Move a corrupt committed checkpoint out of the restore set by
        renaming ``ckpt_N`` to ``ckpt_N.corrupt`` (no longer matches the
        checkpoint name pattern, so ``completed_ids``/``latest`` skip
        it). The data is kept for post-mortem — an operator can rename
        it back after repairing the blob. Returns the quarantine path,
        or None when the directory is already gone."""
        with self._lock_of(self.root):
            d = self._dirname(ckpt_id)
            if not os.path.isdir(d):
                return None
            dst = d + ".corrupt"
            shutil.rmtree(dst, ignore_errors=True)
            try:
                os.replace(d, dst)
            except OSError:
                # rename failed (exotic filesystem): deleting is the
                # only way to guarantee the ladder never retries it
                shutil.rmtree(d, ignore_errors=True)
                return None
            return dst
