"""CheckpointStore: the versioned on-disk layout of aligned snapshots.

Layout under one root directory::

    <root>/
      ckpt_0000000003.inprogress/     # staging: blobs land here first
        reduce_1a2b3c4d__0.blob
        source_5e6f7a8b__0.blob
      ckpt_0000000002/                # committed: manifest present
        manifest.json
        *.blob

Every write is crash-safe by construction: blobs and the manifest are
written to a ``.tmp`` sibling and published with ``os.replace`` (atomic
rename on POSIX), and a checkpoint becomes visible as a whole only when
its staging directory is atomically renamed to the final name. A crash at
any point leaves either the previous committed checkpoint intact or a
``.inprogress`` directory that restore ignores. Retention keeps the last
``retain`` committed checkpoints.

Blob files are named ``<sanitized-op-name>_<crc32>__<replica>.blob`` (the
crc disambiguates op names that sanitize identically — it says nothing
about the blob's CONTENT); each blob pickles ``{"op": <exact name>,
"replica": idx, "state": <replica state dict>}`` so restore matches
replicas by exact name, never by file name.

Content integrity (``WF_CKPT_VERIFY``, on by default): every blob's
sha256 digest is recorded in the manifest at snapshot time, and restore
re-hashes each blob before unpickling it — a torn, truncated, or
bit-flipped blob raises a typed ``CorruptCheckpointError`` naming the
bad file instead of feeding garbage state into the graph. Manifests
written before this scheme carry no ``digests`` map and restore with a
warning, never an error.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import shutil
import threading
import warnings
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..basic import WindFlowError

MANIFEST = "manifest.json"
FORMAT_VERSION = 1

_CKPT_RE = re.compile(r"^ckpt_(\d{10})$")


class CorruptCheckpointError(WindFlowError):
    """A checkpoint failed content verification: a blob's sha256 digest
    does not match the manifest, a manifested blob is missing, the
    manifest itself is undecodable, or a blob cannot be unpickled. The
    message names the bad file. The supervisor's fallback ladder catches
    this and walks to the next-older checkpoint."""


def env_ckpt_verify() -> bool:
    """``WF_CKPT_VERIFY``: write blob digests into manifests and verify
    them on restore. On by default; 0/false/off disables both sides
    (the microbench A/B knob — and an escape hatch, not a config)."""
    v = os.environ.get("WF_CKPT_VERIFY", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def _hash_bytes(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


def _hash_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return "sha256:" + h.hexdigest()


def blob_name(op_name: str, replica_idx: int) -> str:
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in op_name)
    crc = zlib.crc32(op_name.encode("utf-8", "surrogatepass")) & 0xFFFFFFFF
    return f"{safe}_{crc:08x}__{replica_idx}.blob"


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class CheckpointStore:
    # per-root store locks (process-wide): retain-K prune and a
    # concurrent restore's blob reads of the SAME root serialize here,
    # so prune can never delete a checkpoint mid-read — two graph
    # instances (a live coordinator committing, another restoring) may
    # share one root without coordinating
    _root_locks: Dict[str, threading.RLock] = {}
    _root_guard = threading.Lock()

    @classmethod
    def _lock_of(cls, root: str) -> threading.RLock:
        key = os.path.abspath(root)
        with cls._root_guard:
            lock = cls._root_locks.get(key)
            if lock is None:
                lock = cls._root_locks[key] = threading.RLock()
            return lock

    def __init__(self, root: str, retain: int = 3) -> None:
        self.root = root
        self.retain = max(1, int(retain))
        os.makedirs(root, exist_ok=True)
        # digests of staged blobs, keyed ckpt_id -> {fname: "sha256:..."}
        # — hashed from the in-memory payload at write time (free second
        # read avoided); commit() folds them into the manifest
        self._digests: Dict[int, Dict[str, str]] = {}
        self._digest_lock = threading.Lock()
        # cumulative digest-verification failures observed by THIS store
        # instance (surfaced as Checkpoint_verify_failures /
        # windflow_ckpt_verify_failures_total)
        self.verify_failures = 0

    # -- paths -------------------------------------------------------------
    def _dirname(self, ckpt_id: int, staging: bool = False) -> str:
        d = os.path.join(self.root, f"ckpt_{ckpt_id:010d}")
        return d + ".inprogress" if staging else d

    def begin(self, ckpt_id: int) -> None:
        """Start (or restart) staging for a checkpoint: stale debris from
        a crashed attempt at the same id must not leak into the manifest."""
        staging = self._dirname(ckpt_id, staging=True)
        shutil.rmtree(staging, ignore_errors=True)
        os.makedirs(staging, exist_ok=True)
        with self._digest_lock:
            self._digests.pop(ckpt_id, None)

    # -- writes ------------------------------------------------------------
    def write_blob(self, ckpt_id: int, op_name: str, replica_idx: int,
                   state: Any) -> int:
        """Pickle one replica's snapshot into the staging dir (atomic
        tmp+rename). Returns the byte size written."""
        staging = self._dirname(ckpt_id, staging=True)
        os.makedirs(staging, exist_ok=True)
        payload = pickle.dumps(
            {"op": op_name, "replica": replica_idx, "state": state},
            protocol=pickle.HIGHEST_PROTOCOL)
        fname = blob_name(op_name, replica_idx)
        if env_ckpt_verify():
            digest = _hash_bytes(payload)
            with self._digest_lock:
                self._digests.setdefault(ckpt_id, {})[fname] = digest
        _atomic_write(os.path.join(staging, fname), payload)
        return len(payload)

    def staged_blobs(self, ckpt_id: int) -> List[str]:
        staging = self._dirname(ckpt_id, staging=True)
        try:
            return sorted(f for f in os.listdir(staging)
                          if f.endswith(".blob"))
        except FileNotFoundError:
            return []

    def commit(self, ckpt_id: int, manifest: Dict[str, Any]) -> str:
        """Finalize: manifest into staging, then one atomic directory
        rename makes the whole checkpoint visible. Prunes old ones."""
        staging = self._dirname(ckpt_id, staging=True)
        final = self._dirname(ckpt_id)
        manifest = dict(manifest)
        manifest.setdefault("format", FORMAT_VERSION)
        manifest["ckpt_id"] = ckpt_id
        manifest["blobs"] = self.staged_blobs(ckpt_id)
        with self._digest_lock:
            cached = self._digests.pop(ckpt_id, {})
        if env_ckpt_verify():
            # blobs written through another store instance (or with the
            # knob off at write time) aren't in the cache: hash the file
            manifest["digests"] = {
                fname: cached.get(fname)
                or _hash_file(os.path.join(staging, fname))
                for fname in manifest["blobs"]}
        _atomic_write(os.path.join(staging, MANIFEST),
                      json.dumps(manifest, indent=1).encode())
        shutil.rmtree(final, ignore_errors=True)  # same-id re-commit
        os.replace(staging, final)
        self.prune()
        return final

    def prune(self) -> None:
        # the whole sweep holds the per-root store lock: a concurrent
        # restore_from= reading this root (load_states below) holds the
        # same lock for its whole blob read, so retention can never
        # delete a checkpoint out from under it mid-read
        with self._lock_of(self.root):
            done = self.completed_ids()
            for cid in done[:-self.retain]:
                shutil.rmtree(self._dirname(cid), ignore_errors=True)
            # staging debris older than the newest committed checkpoint
            # can never complete (its coordinator is gone) — clean it up
            if done:
                for name in os.listdir(self.root):
                    if name.endswith(".inprogress"):
                        m = _CKPT_RE.match(name[:-len(".inprogress")])
                        if m and int(m.group(1)) <= done[-1]:
                            shutil.rmtree(os.path.join(self.root, name),
                                          ignore_errors=True)

    # -- reads -------------------------------------------------------------
    def completed_ids(self) -> List[int]:
        out = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        for name in names:
            m = _CKPT_RE.match(name)
            if m and os.path.exists(os.path.join(self.root, name, MANIFEST)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self) -> Optional[int]:
        ids = self.completed_ids()
        return ids[-1] if ids else None

    def checkpoint_dir(self, ckpt_id: int) -> Optional[str]:
        """Directory holding a checkpoint's blobs: the committed dir when
        present, else the staging dir (diagnostics/tests only — restore
        goes through ``resolve`` and accepts committed checkpoints only)."""
        final = self._dirname(ckpt_id)
        if os.path.isdir(final):
            return final
        staging = self._dirname(ckpt_id, staging=True)
        return staging if os.path.isdir(staging) else None

    @staticmethod
    def load_manifest(ckpt_dir: str) -> Dict[str, Any]:
        path = os.path.join(ckpt_dir, MANIFEST)
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            raise
        except (ValueError, UnicodeDecodeError, OSError) as e:
            # a torn/garbled manifest is corruption, not a crash: typed
            # so the supervisor's fallback ladder can walk past it
            raise CorruptCheckpointError(
                f"checkpoint manifest {path}: undecodable "
                f"({type(e).__name__}: {e})") from e

    @staticmethod
    def load_blob(ckpt_dir: str, fname: str) -> Dict[str, Any]:
        with open(os.path.join(ckpt_dir, fname), "rb") as f:
            return pickle.load(f)

    @classmethod
    def resolve(cls, path: str) -> Tuple[int, str, Dict[str, Any]]:
        """Resolve a restore target: either one checkpoint directory (has
        a manifest) or a store root (picks the latest committed
        checkpoint). Returns ``(ckpt_id, dir, manifest)``."""
        from ..basic import WindFlowError

        if os.path.exists(os.path.join(path, MANIFEST)):
            manifest = cls.load_manifest(path)
            return int(manifest["ckpt_id"]), path, manifest
        store = cls(path)
        cid = store.latest()
        if cid is None:
            raise WindFlowError(
                f"restore_from={path!r}: no committed checkpoint found "
                "(expected a checkpoint directory with a manifest.json or "
                "a store root containing ckpt_* directories)")
        d = store._dirname(cid)
        return cid, d, cls.load_manifest(d)

    def load_states(self, ckpt_dir: str, manifest: Dict[str, Any]
                    ) -> Dict[Tuple[str, int], Any]:
        """All replica states of one checkpoint, keyed (op name, idx).
        The whole read holds the checkpoint root's store lock, excluding
        a concurrent ``prune`` (a live coordinator committing into the
        same root) for the duration — the blobs named by the manifest
        cannot vanish halfway through the restore.

        With ``WF_CKPT_VERIFY`` on (default), each blob is re-hashed
        against the manifest's digest BEFORE unpickling; any mismatch,
        missing blob, or undecodable pickle raises
        ``CorruptCheckpointError`` naming the bad file. Pre-digest
        manifests (no ``digests`` map) restore with a warning."""
        verify = env_ckpt_verify()
        digests = manifest.get("digests") or {}
        blobs = manifest.get("blobs", [])
        if verify and blobs and not digests:
            warnings.warn(
                f"checkpoint {ckpt_dir} carries no content digests "
                "(written before integrity verification, or with "
                "WF_CKPT_VERIFY=0): restoring unverified",
                RuntimeWarning, stacklevel=2)
        out: Dict[Tuple[str, int], Any] = {}
        with self._lock_of(os.path.dirname(os.path.abspath(ckpt_dir))
                           or self.root):
            for fname in blobs:
                path = os.path.join(ckpt_dir, fname)
                want = digests.get(fname) if verify else None
                if want is not None:
                    try:
                        got = _hash_file(path)
                    except OSError as e:
                        self.verify_failures += 1
                        raise CorruptCheckpointError(
                            f"checkpoint blob {path}: unreadable "
                            f"({type(e).__name__}: {e})") from e
                    if got != want:
                        self.verify_failures += 1
                        raise CorruptCheckpointError(
                            f"checkpoint blob {path}: content digest "
                            f"mismatch (manifest {want}, file {got}) — "
                            "the blob is torn or corrupted on disk")
                try:
                    blob = self.load_blob(ckpt_dir, fname)
                except CorruptCheckpointError:
                    raise
                except Exception as e:
                    # digest matched (or verification off) yet the pickle
                    # is undecodable / the file vanished: still corruption
                    self.verify_failures += 1
                    raise CorruptCheckpointError(
                        f"checkpoint blob {path}: undecodable "
                        f"({type(e).__name__}: {e})") from e
                out[(blob["op"], int(blob["replica"]))] = blob["state"]
        return out

    # -- integrity ---------------------------------------------------------
    def verify(self, ckpt_id: Optional[int] = None) -> Dict[int, Dict[str, Any]]:
        """Offline integrity sweep: re-hash every blob of one (or every)
        committed checkpoint against its manifest, WITHOUT unpickling
        anything. Returns ``{ckpt_id: {"ok", "problems", "blobs",
        "bytes", "digested"}}`` — never raises on corruption, so an
        operator can survey a damaged store in one call."""
        ids = [ckpt_id] if ckpt_id is not None else self.completed_ids()
        report: Dict[int, Dict[str, Any]] = {}
        with self._lock_of(self.root):
            for cid in ids:
                d = self._dirname(cid)
                problems: List[str] = []
                nbytes = 0
                digested = False
                try:
                    manifest = self.load_manifest(d)
                except (FileNotFoundError, CorruptCheckpointError) as e:
                    report[cid] = {"ok": False, "problems": [str(e)],
                                   "blobs": 0, "bytes": 0,
                                   "digested": False}
                    continue
                digests = manifest.get("digests") or {}
                digested = bool(digests)
                for fname in manifest.get("blobs", []):
                    path = os.path.join(d, fname)
                    try:
                        nbytes += os.path.getsize(path)
                        got = _hash_file(path)
                    except OSError as e:
                        problems.append(f"{fname}: unreadable "
                                        f"({type(e).__name__}: {e})")
                        continue
                    want = digests.get(fname)
                    if want is not None and got != want:
                        problems.append(f"{fname}: digest mismatch "
                                        f"(manifest {want}, file {got})")
                report[cid] = {"ok": not problems, "problems": problems,
                               "blobs": len(manifest.get("blobs", [])),
                               "bytes": nbytes, "digested": digested}
        return report

    def quarantine(self, ckpt_id: int) -> Optional[str]:
        """Move a corrupt committed checkpoint out of the restore set by
        renaming ``ckpt_N`` to ``ckpt_N.corrupt`` (no longer matches the
        checkpoint name pattern, so ``completed_ids``/``latest`` skip
        it). The data is kept for post-mortem — an operator can rename
        it back after repairing the blob. Returns the quarantine path,
        or None when the directory is already gone."""
        with self._lock_of(self.root):
            d = self._dirname(ckpt_id)
            if not os.path.isdir(d):
                return None
            dst = d + ".corrupt"
            shutil.rmtree(dst, ignore_errors=True)
            try:
                os.replace(d, dst)
            except OSError:
                # rename failed (exotic filesystem): deleting is the
                # only way to guarantee the ladder never retries it
                shutil.rmtree(d, ignore_errors=True)
                return None
            return dst
