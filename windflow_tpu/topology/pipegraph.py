"""PipeGraph: the streaming environment — build, wire, run, wait.

Parity with ``wf/pipegraph.hpp``:
- ``PipeGraph(name, ExecutionMode, TimePolicy)`` (L545-554);
- ``add_source`` (L593) returns the root MultiPipe;
- ``run`` = ``start`` + ``wait_end`` (L610-764);
- dropped-tuple accounting (L782-785), per-operator stats dump (L464-522),
  dot diagram generation (Graphviz, L525-534).

Wiring rules are described in ``topology/stage.py``; emitter/collector
selection mirrors ``wf/multipipe.hpp:200-362``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..basic import (DEFAULT_BUFFER_CAPACITY, ExecutionMode, OpType,
                     RoutingMode, TimePolicy, WindFlowError, env_flag)
from ..operators.base import BasicOperator
from ..runtime.channel import Channel, InlinePort, QueuePort
from ..runtime.collectors import (AtomicCounter, DPJoinCollector,
                                  IDSequencerCollector, KSlackCollector,
                                  OrderingCollector, WatermarkCollector)
from ..runtime.emitters import (BasicEmitter, BroadcastEmitter, ForwardEmitter,
                                KeyByEmitter, NullEmitter, SplittingEmitter)
from ..runtime.worker import Worker
from .multipipe import MultiPipe
from .stage import Stage


class PipeGraph:
    def __init__(self, name: str = "pipegraph",
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT,
                 time_policy: TimePolicy = TimePolicy.INGRESS_TIME,
                 channel_capacity: int = DEFAULT_BUFFER_CAPACITY) -> None:
        self.name = name
        self.execution_mode = execution_mode
        self.time_policy = time_policy
        self.channel_capacity = channel_capacity
        self._stages: List[Stage] = []
        self._source_pipes: List[MultiPipe] = []
        self._ops: List[BasicOperator] = []
        self._workers: List[Worker] = []
        self.dropped = AtomicCounter()
        self._built = False
        self._started = False
        self._ended = False
        self._monitor = None
        # flight recorder (monitoring/flightrec.py): per-worker event
        # rings + the stall watchdog; with_flight_recorder() or the
        # WF_FLIGHTREC_EVENTS / WF_STALL_SEC env knobs enable them
        self._flightrec_events: Optional[int] = None
        self._recorders: List[Any] = []
        self._watchdog = None
        self.last_postmortem: Optional[str] = None  # newest dump path
        # aligned-barrier checkpointing (windflow_tpu.checkpoint):
        # enabled via with_checkpointing() or the WF_CKPT_INTERVAL /
        # WF_CKPT_DIR env knobs; restore_from enables it implicitly
        self._coordinator = None
        self._ckpt_enabled = False
        self._ckpt_interval: Optional[float] = None
        self._ckpt_dir: Optional[str] = None
        self._ckpt_retain = 3
        # elastic rescaling (windflow_tpu.scaling): live repartitioning
        # via rescale(); with_autoscaler()/WF_AUTOSCALE=1 close the loop
        self._rescale_ctrl = None
        self._autoscale_policy = None
        self._autoscale_enabled = False
        self._autoscaler = None
        self._rescaling = False  # stall watchdog stands down mid-rescale
        # mark-final-then-drop series retirement: replicas removed by a
        # scale-down surface ONCE more (Final=true) in get_stats, then
        # vanish — Prometheus sees a clean series end, not a frozen value
        self._final_series: List[Dict[str, Any]] = []
        # exactly-once sinks (windflow_tpu.sinks.transactional): the
        # graph-wide switch flips every sink that supports the 2PC
        # protocol; per-sink builders (`with_exactly_once()`) opt in
        # individually. Env twin: WF_EXACTLY_ONCE=1
        self._exactly_once = env_flag("WF_EXACTLY_ONCE")
        # self-healing supervision (windflow_tpu.supervision): a
        # supervisor thread auto-recovers the graph from worker deaths
        # and stall episodes under a bounded restart budget; enabled via
        # with_supervision() or WF_SUPERVISE=1. _supervising flips while
        # a recovery is in flight (wait_end spins, watchdog stands down)
        self._supervisor = None
        self._supervise_policy = None
        self._supervise_enabled = env_flag("WF_SUPERVISE")
        self._supervising = False
        # device-health probe (supervision/health.py): dead devices are
        # excluded from rebuilt meshes during supervised recovery;
        # with_device_probe() or WF_HEALTH_PROBE=jax
        self._device_probe = None
        # dead-letter queue (windflow_tpu.supervision.errors): created
        # lazily when any operator carries a quarantining error policy
        self._dlq = None
        # JAX persistent compilation cache (WF_COMPILE_CACHE_DIR /
        # with_compile_cache): supervised restarts and rescales re-use
        # compiled chain programs instead of re-tracing from scratch
        self._compile_cache_dir: Optional[str] = \
            os.environ.get("WF_COMPILE_CACHE_DIR") or None
        # overload protection (windflow_tpu.overload): with_slo(p99_ms)
        # or WF_SLO_P99_MS attach an OverloadGovernor control loop at
        # start() — SLO-breach escalation (tune -> scale -> shed) with
        # hysteresis/cooldown recovery
        self._slo_p99_ms: Optional[float] = None
        self._overload_policy = None
        self._overload_governor = None
        env_slo = os.environ.get("WF_SLO_P99_MS")
        if env_slo:
            try:
                self._slo_p99_ms = float(env_slo)
            except ValueError:
                pass  # malformed knob must not take down the graph
        # compile-stability pre-warm (with_prewarm / WF_PREWARM=1):
        # compile every bucketed device-chain signature at start(),
        # before the sources open, so no retrace lands mid-stream
        self._prewarm_enabled = env_flag("WF_PREWARM")
        self._prewarm_report: Optional[Dict[str, Any]] = None
        env_iv = os.environ.get("WF_CKPT_INTERVAL")
        if env_iv:
            try:
                self.with_checkpointing(interval=float(env_iv))
            except ValueError:
                pass  # malformed knob must not take down the graph
        if os.environ.get("WF_CKPT_DIR"):
            self._ckpt_dir = os.environ["WF_CKPT_DIR"]

    # ------------------------------------------------------------------
    # exactly-once sinks (windflow_tpu.sinks.transactional)
    # ------------------------------------------------------------------
    def with_exactly_once(self) -> "PipeGraph":
        """Graph-wide exactly-once delivery: every sink runs the
        epoch-fenced two-phase commit (buffer/stage per checkpoint
        epoch, pre-commit at the aligned barrier, commit atomically on
        coordinator finalize). Requires ``with_checkpointing``; a sink
        family that cannot honor the protocol makes ``start()`` refuse
        loudly rather than silently downgrade the guarantee. Env twin:
        ``WF_EXACTLY_ONCE=1``."""
        if self._started:
            raise WindFlowError("with_exactly_once after start()")
        self._exactly_once = True
        return self

    # ------------------------------------------------------------------
    # overload protection (windflow_tpu.overload)
    # ------------------------------------------------------------------
    def with_slo(self, p99_ms: float, policy: Optional[Any] = None
                 ) -> "PipeGraph":
        """Declare the graph's end-to-end p99 latency budget
        (milliseconds) and attach the :class:`OverloadGovernor` at
        ``start()``: when the sink-side windowed p99 breaches the SLO the
        governor walks an escalation ladder — shrink dispatch
        depth/output batching, scale the bottleneck operator (bounded by
        MAX_PAR), then admission-control the sources (token-bucket rate
        limiting + the configured shed policy) — and recovers with
        hysteresis and cooldown. ``policy`` is a
        :class:`GovernorPolicy` (None = defaults, tunable via the
        ``WF_SLO_*`` / ``WF_SHED_*`` env knobs). Per-source budgets via
        ``Source_Builder.with_slo``; the tightest declared budget
        governs. Sink-side latency sampling is enabled automatically
        (1/16) when not already configured — the governor is blind
        without e2e samples. Env twin: ``WF_SLO_P99_MS``."""
        if self._started:
            raise WindFlowError("with_slo after start()")
        if p99_ms <= 0:
            raise WindFlowError("with_slo: p99_ms must be > 0")
        self._slo_p99_ms = float(p99_ms)
        self._overload_policy = policy
        return self

    def _effective_slo_ms(self) -> Optional[float]:
        """Tightest declared budget: graph-level with_slo/WF_SLO_P99_MS
        and every source builder's with_slo."""
        budgets = [self._slo_p99_ms] if self._slo_p99_ms else []
        budgets += [op.slo_p99_ms for op in self._ops
                    if getattr(op, "slo_p99_ms", None)]
        return min(budgets) if budgets else None

    def _setup_overload_governor(self) -> None:
        """Create the governor (started with the other control threads).
        Validation is LOUD and up-front: a key_priority shed policy
        without priorities would only fail mid-surge otherwise."""
        slo_ms = self._effective_slo_ms()
        if slo_ms is None and self._overload_policy is None:
            return
        from ..overload import GovernorPolicy, OverloadGovernor
        policy = self._overload_policy
        if policy is None:
            policy = GovernorPolicy(slo_p99_ms=slo_ms)
        elif slo_ms is not None and slo_ms * 1e3 < policy.slo_us:
            policy.slo_us = slo_ms * 1e3  # a source declared tighter
        if policy.shed_policy == "key_priority":
            for op in self._ops:
                if op.op_type == OpType.SOURCE \
                        and getattr(op, "priority_fn", None) is None:
                    raise WindFlowError(
                        f"with_slo: shed policy 'key_priority' needs "
                        f"with_priority(fn) on source {op.name!r} — "
                        "records have no priority to shed by otherwise")
        self._overload_governor = OverloadGovernor(self, policy)

    def _ensure_slo_sampling(self) -> None:
        """BEFORE ``_build`` (replica histograms allocate at replica
        construction): the governor needs sink-side e2e samples, so an
        SLO declaration turns on 1/16 sampling for sinks (and 1/16
        source stamping) when nothing configured it."""
        if self._effective_slo_ms() is None:
            return
        from ..monitoring.tracing import env_sample_every
        if env_sample_every() > 0:
            return  # WF_LATENCY_SAMPLE already stamps the stream
        for op in self._ops:
            if op.op_type in (OpType.SOURCE, OpType.SINK) \
                    and op.latency_sample is None:
                op.latency_sample = 16

    # ------------------------------------------------------------------
    # compile-stability pre-warm (ROADMAP: kill retrace storms)
    # ------------------------------------------------------------------
    def with_prewarm(self) -> "PipeGraph":
        """Pre-warm the device plane at ``start()``: every stateless
        chain program compiles for every power-of-two bucket capacity up
        to the graph's largest staging batch, BEFORE the sources open —
        so a ragged stream (whose tail batches and keyed repartitions
        land in smaller buckets) never pays a retrace mid-stream.
        Stateful programs (grid scans, FFAT forests) key their
        signatures on runtime cardinality and are skipped (the report
        names them). Compiles land in ``Compile_*`` stats during
        warm-up; ``Compile_count`` then stays flat. Results in
        ``prewarm_report`` / ``get_stats()["Prewarm"]``. Env twin:
        ``WF_PREWARM=1``; pairs with ``with_compile_cache`` so restarts
        re-warm from disk in milliseconds."""
        if self._started:
            raise WindFlowError("with_prewarm after start()")
        self._prewarm_enabled = True
        return self

    def _bucket_caps(self) -> List[int]:
        """The finite bucket set a run can see: powers of two from the
        minimum staging bucket up to the largest declared output batch
        (ragged tails keep the full bucket; device-side keyed
        repartition and compaction produce the smaller ones)."""
        from ..tpu.batch import bucket_capacity
        max_obs = max((op.output_batch_size for op in self._ops),
                      default=0)
        top = bucket_capacity(max(1, max_obs))
        caps, c = [], bucket_capacity(1)
        while c <= top:
            caps.append(c)
            c <<= 1
        return caps

    def _prewarm_device_programs(self) -> None:
        if not any(getattr(op, "is_tpu", False) for op in self._ops):
            # CPU-plane graph: nothing compiles, and we must not drag
            # the device plane (jax) in just to find that out
            self._prewarm_report = {"bucket_caps": [],
                                    "signatures_compiled": 0,
                                    "skipped": ["no device stages"],
                                    "elapsed_s": 0.0}
            return
        t0 = time.monotonic()
        caps = self._bucket_caps()
        warmed = 0
        skipped: List[str] = []
        for s in self._stages:
            first = s.first_op
            if not getattr(first, "is_tpu", False):
                continue
            label = s.describe()
            for r in {id(r): r for r in first.replicas}.values():
                pw = getattr(r, "prewarm", None)
                if pw is None:
                    skipped.append(f"{label}: no prewarm hook "
                                   f"({type(r).__name__})")
                    continue
                n = pw(caps)
                if n is None:
                    skipped.append(f"{label}: runtime-dependent "
                                   "signature (stateful/inferred schema)")
                else:
                    warmed += n
        self._prewarm_report = {
            "bucket_caps": caps,
            "signatures_compiled": warmed,
            "skipped": skipped,
            "elapsed_s": round(time.monotonic() - t0, 4),
        }

    @property
    def prewarm_report(self) -> Optional[Dict[str, Any]]:
        return self._prewarm_report

    # ------------------------------------------------------------------
    # self-healing supervision (windflow_tpu.supervision)
    # ------------------------------------------------------------------
    def with_supervision(self, policy: Optional[Any] = None) -> "PipeGraph":
        """Auto-recover the whole graph from worker deaths and
        stall-watchdog episodes: a supervisor tears the runtime plane
        down, restores from the latest committed checkpoint, resumes the
        sources from their recorded positions and restarts — under a
        jittered exponential-backoff ``RestartPolicy`` with a bounded
        restart budget (budget exhausted => the aggregated error raises
        in ``wait_end``). Exactly-once sinks stay duplicate-free across
        restarts. Enables checkpointing implicitly when not configured
        (set an interval for bounded replay). Env twins: ``WF_SUPERVISE=1``
        plus the ``WF_SUPERVISE_*`` policy knobs."""
        if self._started:
            raise WindFlowError("with_supervision after start()")
        self._supervise_enabled = True
        self._supervise_policy = policy
        if not self._ckpt_enabled:
            self.with_checkpointing()
        return self

    def with_device_probe(self, probe: Any) -> "PipeGraph":
        """Install a device-health probe (``supervision.health``): during
        every supervised recovery the probe's dead devices are excluded
        from the rebuilt device meshes, so mesh operators come back on
        the surviving chips with their sharded state relayouted
        byte-identically; the graph then runs degraded
        (``Recovery_degraded_devices`` > 0, the overload governor sheds
        instead of scaling) until the probe sees the device return and
        one planned restart re-expands to full shape. Env twin:
        ``WF_HEALTH_PROBE=jax`` (paced by ``WF_HEALTH_PROBE_INTERVAL``).
        Implies supervision's value only under supervision — without a
        supervisor the probe is never consulted."""
        if self._started:
            raise WindFlowError("with_device_probe after start()")
        self._device_probe = probe
        return self

    def failure_domains(self) -> Dict[int, List[str]]:
        """Device id -> mesh operators whose sharded state lives on it
        (built replicas only). The unit of loss for device failover."""
        from ..supervision.health import failure_domain_map
        return failure_domain_map(self)

    def with_compile_cache(self, cache_dir: str) -> "PipeGraph":
        """Point JAX's persistent compilation cache at ``cache_dir`` so
        supervised restarts and rescales re-use compiled device programs
        (every chain signature otherwise re-traces+recompiles on each
        rebuild). Env twin: ``WF_COMPILE_CACHE_DIR``."""
        if self._started:
            raise WindFlowError("with_compile_cache after start()")
        self._compile_cache_dir = cache_dir
        return self

    def _setup_compile_cache(self) -> None:
        """Wire the persistent compilation cache before the first device
        program is traced (called from ``start``; the first rung of the
        ROADMAP compile-stability item). Thresholds drop to zero so even
        small chain programs persist — a streaming graph re-runs the
        SAME signatures forever, which is the cache's best case."""
        if not self._compile_cache_dir:
            return
        import jax
        os.makedirs(self._compile_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir",
                          self._compile_cache_dir)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              0)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
        except (AttributeError, ValueError):
            pass  # older jax: directory alone still enables the cache

    def _capture_initial_positions(self) -> None:
        """Supervision prerequisite (before the first tuple ships): each
        replayable source replica's STARTING cursor. A failure before
        any checkpoint has committed leaves nothing to restore — the
        supervisor then resets sources to these positions (a full
        replay; exactly-once sinks make it duplicate-free) instead of
        silently resuming from the in-memory cursor and losing the
        prefix that sat in the discarded channels."""
        from ..operators.base import arity
        from ..operators.source import Source as _PlainSource
        self._initial_positions: Dict[Any, Any] = {}
        for s in self._stages:
            if not s.is_source or not isinstance(s.first_op, _PlainSource):
                continue
            op = s.first_op
            snap = getattr(op.func, "snapshot_position", None)
            if snap is None:
                continue
            for r in op.replicas:
                pos = r._restore_position  # a restore_from= start
                if pos is None:
                    pos = (snap(r.context) if arity(snap) >= 1 else snap())
                self._initial_positions[(op.name, r.idx)] = pos

    def dead_letter_queue(self):
        """The graph's quarantine side-channel (created on first use; see
        ``windflow_tpu.supervision.errors.DeadLetterQueue``)."""
        if self._dlq is None:
            from ..supervision.errors import DeadLetterQueue
            self._dlq = DeadLetterQueue(self.name)
        return self._dlq

    def dead_letters(self) -> List[Dict[str, Any]]:
        """Records quarantined by DEAD_LETTER error policies (payload,
        exception metadata, traceback), newest last."""
        return [] if self._dlq is None else self._dlq.records()

    def _negotiate_error_policies(self) -> None:
        """First ``_build``: refuse meaningless policies loudly and
        inject the graph's dead-letter queue into every policy that can
        quarantine but was not given an explicit DLQ."""
        for op in self._ops:
            pol = getattr(op, "error_policy", None)
            if pol is None or pol.is_fail:
                continue
            if op.op_type == OpType.SOURCE:
                raise WindFlowError(
                    f"with_error_policy: source {op.name!r} drives its own "
                    "generation loop — there is no per-record invocation "
                    "to contain; use with_supervision() for source "
                    "failures")
            if pol.may_dead_letter:
                # per-OP attribute, never the policy object: the
                # ErrorPolicy.DEAD_LETTER singleton is shared across
                # graphs, and storing one graph's DLQ on it would route
                # every later graph's quarantine into the wrong queue
                # explicit is-None: an (empty) user-provided DLQ is falsy
                op._dlq = pol.dlq if pol.dlq is not None \
                    else self.dead_letter_queue()

    def _negotiate_mesh_checkpoint(self) -> None:
        """Guarantee negotiation for the mesh plane (first ``_build``):
        a mesh operator without a sharded snapshot/restore path under
        checkpointing would produce checkpoints that silently omit its
        device-mesh state — and could never restore it. Refuse loudly
        instead. Every in-tree mesh operator is snapshot-capable; this
        is the standing fallback for any future mesh op that is not."""
        if not self._ckpt_enabled:
            return
        for op in self._ops:
            if getattr(op, "is_mesh", False) \
                    and not getattr(op, "mesh_snapshot_capable", False):
                raise WindFlowError(
                    f"with_checkpointing: mesh operator {op.name!r} "
                    f"({type(op).__name__}) has no sharded "
                    "snapshot/restore path — a checkpoint would silently "
                    "omit its device-mesh state and a restore could not "
                    "rebuild it; run this graph without checkpointing/"
                    "supervision or use a snapshot-capable mesh operator")

    def _negotiate_exactly_once(self) -> None:
        """Guarantee negotiation (first ``_build``): flip graph-wide
        exactly-once onto every sink, then verify every exactly-once
        sink can actually deliver it — loudly, because a guarantee that
        silently downgrades is worse than a refusal."""
        sinks = [op for op in self._ops if op.op_type == OpType.SINK]
        if self._exactly_once:
            for op in sinks:
                if not getattr(op, "supports_exactly_once", False):
                    raise WindFlowError(
                        f"with_exactly_once: sink {op.name!r} "
                        f"({type(op).__name__}) does not implement the "
                        "transactional sink protocol (precommit_epoch / "
                        "commit-on-finalize); it would deliver "
                        "at-least-once and break the graph guarantee")
                op.exactly_once = True
        eo_sinks = [op for op in sinks
                    if getattr(op, "exactly_once", False)]
        for op in eo_sinks:
            if not getattr(op, "supports_exactly_once", False):
                raise WindFlowError(
                    f"sink {op.name!r} ({type(op).__name__}) has "
                    "exactly_once set but does not implement the "
                    "transactional sink protocol")
        if eo_sinks and not self._ckpt_enabled:
            raise WindFlowError(
                "exactly-once sinks need the checkpoint plane that "
                f"drives their commits: sink(s) "
                f"{[op.name for op in eo_sinks]} request exactly-once "
                "but checkpointing is off — call with_checkpointing(...) "
                "(or set WF_CKPT_INTERVAL) before start()")

    # ------------------------------------------------------------------
    # checkpointing configuration
    # ------------------------------------------------------------------
    def with_checkpointing(self, interval: Optional[float] = None,
                           store_dir: Optional[str] = None,
                           retain: int = 3) -> "PipeGraph":
        """Enable aligned-barrier checkpointing (windflow_tpu.checkpoint).

        ``interval`` (seconds) drives periodic checkpoints; None disables
        the timer — checkpoints then happen only on explicit triggers
        (``SourceShipper.request_checkpoint()`` or
        ``graph.trigger_checkpoint()``). ``store_dir`` is the on-disk
        store root (default: ``WF_CKPT_DIR``, else
        ``wf_checkpoints/<graph name>``); the last ``retain`` committed
        checkpoints are kept. Env twins: ``WF_CKPT_INTERVAL`` /
        ``WF_CKPT_DIR``."""
        if self._started:
            raise WindFlowError("with_checkpointing after start()")
        self._ckpt_enabled = True
        if interval is not None:
            self._ckpt_interval = float(interval)
        if store_dir is not None:
            self._ckpt_dir = store_dir
        self._ckpt_retain = retain
        return self

    # ------------------------------------------------------------------
    # elastic rescaling (windflow_tpu.scaling)
    # ------------------------------------------------------------------
    def with_autoscaler(self, policy: Optional[Any] = None) -> "PipeGraph":
        """Attach the autoscaler control loop: a policy thread watches
        the per-operator backpressure/starvation gauges and e2e latency
        and rescales the bottleneck operator up (idle operators down)
        under hysteresis and cooldown. ``policy`` is an
        ``AutoscalePolicy`` (None = defaults, tunable via the
        ``WF_AUTOSCALE_*`` env knobs). Requires checkpointing — enabled
        implicitly here when not already configured. Env twin:
        ``WF_AUTOSCALE=1``."""
        if self._started:
            raise WindFlowError("with_autoscaler after start()")
        self._autoscale_enabled = True
        self._autoscale_policy = policy
        if not self._ckpt_enabled:
            self.with_checkpointing()
        return self

    def _rescale_controller(self):
        if self._rescale_ctrl is None:
            from ..scaling.controller import RescaleController
            self._rescale_ctrl = RescaleController(self)
        return self._rescale_ctrl

    def rescale(self, op_name: str, parallelism: int,
                timeout_s: Optional[float] = None) -> Any:
        """LIVE rescale of one operator (its whole chained stage) to a
        new parallelism: trigger an aligned checkpoint, quiesce at the
        barrier, rebuild the stage's replica list and every affected
        routing table, restore the repartitioned keyed blobs, resume —
        without replaying from source-zero. Returns a ``RescaleReport``
        with the measured ``checkpoint_s`` / ``pause_s`` / ``total_s``.
        Raises ``WindFlowError`` for non-repartitionable operators
        (global reduce, BROADCAST windows, DP join, persistent sqlite
        state, sources) and on quiesce timeout (``WF_CKPT_TIMEOUT``)."""
        self._rescaling = True
        try:
            return self._rescale_controller().rescale(op_name, parallelism,
                                                      timeout_s)
        finally:
            self._rescaling = False

    def _note_retired_replicas(self, stage, new_n: int) -> None:
        """Capture the final stats of replicas a scale-down removes
        (mark-final-then-drop: exported once more, then gone)."""
        for op in stage.ops:
            if getattr(op, "_fused_hidden", False):
                continue
            label = getattr(op, "_fused_stage_label", None) or op.name
            finals = []
            for r in op.replicas[new_n:]:
                d = r.stats.to_dict()
                d["Final"] = True
                finals.append(d)
            if finals:
                self._final_series.append({
                    "name": label, "kind": type(op).__name__,
                    "parallelism": 0, "retired": True,
                    "replicas": finals})

    def _rebuild_runtime(self) -> None:
        """Discard the runtime plane (replicas, channels, collectors,
        workers) and rebuild it from the — possibly re-parallelized —
        stage IR. Callers (the rescale controller) own quiescing: every
        old worker must already be parked or joined. Flight-recorder
        rings of old workers stay registered so the Perfetto timeline
        shows the rescale seam in one trace."""
        for s in self._stages:
            s.channels = []
            s.workers = []
            for op in s.ops:
                op.replicas = []
        self._workers = []
        self._built = False
        self._build()

    def _stage_flightrec_events_max(self) -> int:
        """Largest flight-ring capacity any stage runs with (the rescale
        controller sizes its own ring to match; 0 = recording off)."""
        return max((self._stage_flightrec_events(s) for s in self._stages),
                   default=0)

    def _worker_diagnostics(self, names: List[str]) -> str:
        """Per-worker evidence for checkpoint-timeout errors: crash
        tracebacks (``Worker_last_error``) and stall-watchdog flags for
        the named workers, when available."""
        parts = []
        stalled = set(getattr(self._watchdog, "fired", []) or [])
        for w in self._workers:
            if w.name not in names:
                continue
            if w.error is not None:
                parts.append(f"{w.name} died: {type(w.error).__name__}: "
                             f"{w.error}")
                continue
            stats = w._stats()
            last = getattr(stats, "worker_last_error", None) if stats \
                else None
            if last:
                parts.append(f"{w.name} last error: "
                             f"{last.strip().splitlines()[-1]}")
            if w.name in stalled:
                parts.append(f"{w.name} flagged by the stall watchdog")
        return "; ".join(parts)

    # ------------------------------------------------------------------
    # flight recorder (monitoring/flightrec.py)
    # ------------------------------------------------------------------
    def with_flight_recorder(self, events: int = 0) -> "PipeGraph":
        """Enable the per-worker flight recorder: every worker gets a
        fixed-size single-writer ring of ``events`` span events
        (default ``WF_FLIGHTREC_EVENTS`` or 4096). Export via
        ``dump_trace(path)``, the ``MonitoringServer`` ``GET /trace``
        window, or the automatic post-mortem on a worker crash /
        stall-watchdog fire."""
        if self._started:
            raise WindFlowError("with_flight_recorder after start()")
        from ..monitoring.flightrec import (DEFAULT_EVENTS,
                                            env_flightrec_events)
        self._flightrec_events = (int(events) if events and events > 0
                                  else env_flightrec_events()
                                  or DEFAULT_EVENTS)
        return self

    def _stage_flightrec_events(self, stage: Stage) -> int:
        """Ring capacity for one stage's workers: the largest per-op
        builder override (``with_flight_recorder(events=N)``), else the
        graph-level setting, else ``WF_FLIGHTREC_EVENTS`` (0 = off)."""
        from ..monitoring.flightrec import env_flightrec_events
        per_op = max((op.flightrec_events or 0 for op in stage.ops),
                     default=0)
        if per_op > 0:
            return per_op
        if self._flightrec_events:
            return self._flightrec_events
        return env_flightrec_events()

    def trace_document(self, stacks: bool = False,
                       extra: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
        """The graph's flight rings as a Chrome trace-event document
        (empty ``traceEvents`` when no recorder is enabled)."""
        from ..monitoring.flightrec import thread_stacks, to_chrome_trace
        return to_chrome_trace(
            self._recorders,
            stacks=thread_stacks() if stacks else None, extra=extra)

    def dump_trace(self, path: str, stacks: bool = False) -> str:
        """Write the flight-recorder timeline as Chrome/Perfetto trace
        JSON (loads in ``chrome://tracing`` / https://ui.perfetto.dev).
        ``stacks=True`` adds ``sys._current_frames()`` for every runtime
        thread (the post-mortem dumps always do)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.trace_document(stacks=stacks), f)
        return path

    def _postmortem_path(self, kind: str, wname: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in f"{self.name}_{kind}_{wname}")
        log_dir = os.environ.get("WF_LOG_DIR", "log")
        return os.path.join(log_dir, f"{safe}.json")

    def _crash_dump(self, worker, exc: BaseException) -> None:
        """Automatic post-mortem on a worker death: the whole graph's
        rings + thread stacks + the traceback, so the runs where a
        timeline matters most leave evidence behind."""
        import traceback
        try:
            path = self._postmortem_path("crash", worker.name)
            doc = self.trace_document(stacks=True, extra={
                "crashedWorker": worker.name,
                "exception": "".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__))})
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f)
            self.last_postmortem = path
        except Exception:
            pass  # the dump must never mask the original error

    def _stall_dump(self, wname: str) -> None:
        """Stall-watchdog fire: same dump shape as a crash, flagged with
        the stalled worker (its stack shows WHERE it is wedged)."""
        try:
            path = self._postmortem_path("stall", wname)
            doc = self.trace_document(stacks=True,
                                      extra={"stalledWorker": wname})
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f)
            self.last_postmortem = path
        except Exception:
            pass

    def trigger_checkpoint(self, wait: bool = False,
                           timeout_s: Optional[float] = None
                           ) -> Optional[int]:
        """Force a checkpoint epoch now (sources inject barriers at their
        next tuple boundary). Returns the checkpoint id, or None when
        checkpointing is not enabled/running. With ``wait=True``, blocks
        until the epoch commits and raises a descriptive
        ``WindFlowError`` naming the unacked workers if it times out
        (``timeout_s``, default ``WF_CKPT_TIMEOUT``)."""
        if self._coordinator is None:
            return None
        cid = self._coordinator.trigger(force=True)
        if wait and cid is not None:
            self._coordinator.wait_committed(cid, timeout_s)
        return cid

    def _ckpt_store_dir(self) -> str:
        if self._ckpt_dir:
            return self._ckpt_dir
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in self.name) or "pipegraph"
        return os.path.join("wf_checkpoints", safe)

    def _setup_checkpointing(self, restore_from: Optional[str]):
        """Create store+coordinator (before _build so _make_workers can
        wire them) and resolve the restore target. Returns
        ``(ckpt_dir, manifest)`` or ``(None, None)``."""
        from ..checkpoint import CheckpointCoordinator, CheckpointStore

        resolved = None
        if restore_from is not None:
            resolved = CheckpointStore.resolve(restore_from)
            if not self._ckpt_enabled:
                # restoring implies checkpointing: keep writing new
                # checkpoints into the same store unless told otherwise
                self._ckpt_enabled = True
                if self._ckpt_dir is None:
                    self._ckpt_dir = os.path.dirname(resolved[1])
        if not self._ckpt_enabled:
            return None, None
        store = CheckpointStore(self._ckpt_store_dir(),
                                retain=self._ckpt_retain)
        self._coordinator = CheckpointCoordinator(
            store, self.name, interval_s=self._ckpt_interval)
        if resolved is not None:
            cid, ckpt_dir, manifest = resolved
            # new epochs continue after the restored one; sources bind
            # their injection cursor to this BEFORE any trigger fires
            self._coordinator.requested_id = cid
            self._coordinator.last_completed_id = cid
            return ckpt_dir, manifest
        return None, None

    def _restore_replicas(self, ckpt_dir: str, manifest: Dict[str, Any]
                          ) -> None:
        self._restore_states(
            self._coordinator.store.load_states(ckpt_dir, manifest))

    def _restore_states(self, states: Dict[Any, Any]) -> None:
        """Push every blob's state into the matching rebuilt replica.
        Topology mismatches fail loudly: silently dropping state would
        trade a crash for wrong answers."""
        by_name = {op.name: op for op in self._ops}
        for (op_name, idx), state in states.items():
            op = by_name.get(op_name)
            if op is None:
                raise WindFlowError(
                    f"restore: checkpoint has state for operator "
                    f"{op_name!r} which this graph does not contain")
            if getattr(op, "_fused_hidden", False):
                raise WindFlowError(
                    f"restore: checkpoint holds standalone state for "
                    f"{op_name!r}, but this graph fuses it into the "
                    "device chain "
                    f"{op.replicas[0].fused_name!r} — the checkpointed "
                    "topology was fused differently (match WF_TPU_FUSION "
                    "/ the chain() calls of the original graph)")
            if idx >= len(op.replicas):
                raise WindFlowError(
                    f"restore: operator {op_name!r} was checkpointed with "
                    f"parallelism > {len(op.replicas)}; a cross-restart "
                    "parallelism change needs a LIVE rescale "
                    "(graph.rescale) — restore_from requires the "
                    "checkpointed topology")
            replica = op.replicas[idx]
            if state.get("__fused__") is not None \
                    and getattr(replica, "fused_signature", None) is None:
                raise WindFlowError(
                    f"restore: checkpoint blob for {op_name!r} holds a "
                    f"fused device chain {'∘'.join(state['__fused__'])!r}, "
                    "but this graph runs the operator standalone — the "
                    "checkpointed topology was fused differently (match "
                    "WF_TPU_FUSION / the chain() calls of the original "
                    "graph)")
            if "txn_last_epoch" in state \
                    and not hasattr(replica, "precommit_epoch"):
                raise WindFlowError(
                    f"restore: checkpoint blob for {op_name!r} was taken "
                    "by an exactly-once sink, but this graph runs the "
                    "sink at-least-once — staged epochs would neither "
                    "commit nor abort; enable with_exactly_once() to "
                    "match the checkpointed guarantee")
            state = dict(state)
            em_state = state.pop("__emitter__", None)
            coll_state = state.pop("__collector__", None)
            replica.restore_state(state)
            if em_state is not None and replica.emitter is not None:
                replica.emitter.restore_emitter_state(em_state)
            coll = getattr(replica, "_collector", None)
            if coll_state is not None:
                if coll is not None:
                    coll.restore_state(coll_state)
                elif any(coll_state.get(k) for k in
                         ("bufs", "heap", "pending")):
                    # buffered pre-barrier MESSAGES with nowhere to go
                    # would silently vanish — refuse instead
                    raise WindFlowError(
                        f"restore: {op_name!r} replica {idx} has buffered "
                        "collector state but the rebuilt stage has no "
                        "collector (input fan-in changed); cannot restore "
                        "without losing data")

    # ------------------------------------------------------------------
    def _register_op(self, op: BasicOperator) -> None:
        self._ops.append(op)

    def add_source(self, source_op: BasicOperator) -> MultiPipe:
        if self._started:
            raise WindFlowError("cannot add sources after start()")
        if source_op.op_type != OpType.SOURCE:
            raise WindFlowError("add_source requires a Source-kind operator")
        mp = MultiPipe(self)
        mp._claim(source_op)
        stage = Stage(source_op)
        self._stages.append(stage)
        mp.tail_groups = [[stage]]
        self._source_pipes.append(mp)
        return mp

    # ------------------------------------------------------------------
    # build & wiring
    # ------------------------------------------------------------------
    def _build(self) -> None:
        if self._built:
            return
        self._built = True
        # guarantee negotiation BEFORE replica construction (replica
        # classes are chosen by op.exactly_once) — here rather than in
        # start() because get_num_threads() builds too, and a build that
        # silently ignored the requested guarantee would be worse than
        # the refusal
        self._negotiate_exactly_once()
        self._negotiate_error_policies()
        self._negotiate_mesh_checkpoint()
        for s in self._stages:
            for op in s.ops:
                op.configure(self.execution_mode, self.time_policy)
            if s.is_fused_tpu:
                # chained device stage: ONE fused replica per slot runs
                # the whole chain as a single XLA program (fused_ops.py;
                # the factory picks the window-terminated variant when
                # the chain ends in Ffat_Windows_TPU). Every sub-op
                # aliases the fused replica list so edge wiring
                # (first_op/last_op.replicas) stays uniform.
                from ..tpu.fused_ops import make_fused_replica
                fused = [make_fused_replica(s.ops, i)
                         for i in range(s.parallelism)]
                label = s.describe()
                for op in s.ops:
                    op.replicas = fused
                    op._fused_hidden = op is not s.first_op
                s.first_op._fused_stage_label = label
            else:
                for op in s.ops:
                    op.build_replicas()
        # channels (one per consumer replica); the native C++ ring stays
        # OPT-IN (WF_NATIVE_CHANNELS=1): measured 2026-07-29, the Python
        # deque+Condition channel moves ~1.0M msg/s vs ~0.3M for the
        # ctypes ring — per-call ctypes overhead dominates at message
        # granularity, and inter-stage traffic is already batch-granular
        channel_cls = Channel
        if env_flag("WF_NATIVE_CHANNELS"):
            from ..native import NativeChannel, native_available
            if native_available():
                channel_cls = NativeChannel
        for s in self._stages:
            if not s.is_source:
                s.channels = [channel_cls(self.channel_capacity)
                              for _ in range(s.parallelism)]
        # intra-stage chain wiring (fused InlinePort edges); fused device
        # stages have no intra-stage edges at all — the chain is one
        # program inside one replica
        for s in self._stages:
            if s.is_fused_tpu:
                continue
            for a, b in zip(s.ops[:-1], s.ops[1:]):
                for i in range(s.parallelism):
                    em = ForwardEmitter(1, 0, self.execution_mode)
                    em.punct_generation = False
                    em.set_ports([InlinePort(b.replicas[i])])
                    a.replicas[i].set_emitter(em)
        # inter-stage wiring, consumer-driven so that input channel indices
        # follow upstream order (join stream A channels first)
        for c in self._stages:
            for edge in c.upstreams:
                self._wire_edge(edge.stage, edge.branch, c)
        # terminal emitters
        for s in self._stages:
            last = s.last_op
            for r in last.replicas:
                if r.emitter is None:
                    r.set_emitter(NullEmitter())
        # split stages: assemble per-replica splitting emitters
        for s in self._stages:
            if s.is_split:
                for i, r in enumerate(s.last_op.replicas):
                    inner = r._split_inner  # branch -> emitter
                    ems = [inner.get(b) for b in range(len(s.split_branches))]
                    missing = [b for b, e in enumerate(ems) if e is None]
                    if missing:
                        raise WindFlowError(
                            f"split stage {s.describe()}: branches {missing} "
                            f"have no operators")
                    logic = s.split_logic
                    if getattr(s.last_op, "is_tpu", False):
                        from ..tpu.emitters_tpu import TPUSplittingEmitter
                        se: BasicEmitter = TPUSplittingEmitter(
                            logic, ems, self.execution_mode)
                    else:
                        if isinstance(logic, str):
                            field = logic
                            logic = (lambda t, _f=field:
                                     t[_f] if isinstance(t, dict)
                                     else getattr(t, _f))
                        se = SplittingEmitter(logic, ems,
                                              self.execution_mode)
                    r.set_emitter(se)
        # collectors + workers
        for s in self._stages:
            self._make_workers(s)

    def _wire_edge(self, producer: Stage, branch: Optional[int],
                   consumer: Stage) -> None:
        """Create one emitter per producer replica targeting all consumer
        replicas (or one-to-one for same-parallelism FORWARD, reference
        Case 2)."""
        first = consumer.first_op
        routing = first.input_routing
        obs = producer.last_op.output_batch_size
        n_dests = consumer.parallelism
        p_tpu = getattr(producer.last_op, "is_tpu", False)
        c_tpu = getattr(first, "is_tpu", False)
        if c_tpu and not p_tpu and obs <= 0:
            # reference: a GPU operator's predecessor must declare an output
            # batch size (wf/multipipe.hpp:457-460)
            raise WindFlowError(
                f"operator {producer.last_op.name!r} feeds TPU operator "
                f"{first.name!r} but declares no output batch size; call "
                "with_output_batch_size(n) on the producer")
        one_to_one = (routing is RoutingMode.FORWARD
                      and branch is None
                      and not (c_tpu and not p_tpu)
                      and producer.parallelism == n_dests)
        if routing is RoutingMode.BROADCAST:
            for op in consumer.ops:
                for r in op.replicas:
                    r.copy_on_write = True
        for pi, pr in enumerate(producer.last_op.replicas):
            em = self._create_edge_emitter(first, routing, obs, n_dests,
                                           p_tpu, c_tpu, one_to_one)
            if one_to_one:
                ports = [QueuePort(consumer.channels[pi])]
            else:
                ports = [QueuePort(ch) for ch in consumer.channels]
            em.set_ports(ports)
            if branch is None:
                pr.set_emitter(em)
            else:
                if not hasattr(pr, "_split_inner"):
                    pr._split_inner = {}
                pr._split_inner[branch] = em
                em.stats = pr.stats

    def _create_edge_emitter(self, first: BasicOperator, routing: RoutingMode,
                             obs: int, n_dests: int, p_tpu: bool,
                             c_tpu: bool, one_to_one: bool) -> BasicEmitter:
        """Emitter kind per (device-plane, routing) — the reference's
        create_emitter (``wf/multipipe.hpp:248-362``) plus the GPU-emitter
        template cases (<inputGPU, outputGPU>)."""
        if c_tpu and not p_tpu:  # CPU -> TPU staging boundary
            from ..tpu.emitters_tpu import TPUStageEmitter
            routing_name = ("keyby" if routing is RoutingMode.KEYBY else
                            "broadcast" if routing is RoutingMode.BROADCAST
                            else "forward")
            return TPUStageEmitter(n_dests, obs,
                                   getattr(first, "schema", None),
                                   first.key_extractor,
                                   routing_name, self.execution_mode,
                                   key_field=first.key_field,
                                   key_fields=getattr(first, "key_fields",
                                                      None))
        if p_tpu and c_tpu:  # device -> device
            from ..tpu.emitters_tpu import (TPUBroadcastEmitter,
                                            TPUForwardEmitter,
                                            TPUKeyByEmitter)
            if routing is RoutingMode.KEYBY:
                return TPUKeyByEmitter(first.key_extractor, n_dests,
                                       self.execution_mode,
                                       key_field=first.key_field,
                                       key_fields=getattr(first,
                                                          "key_fields",
                                                          None))
            if routing is RoutingMode.BROADCAST:
                em = TPUBroadcastEmitter(n_dests, 0, self.execution_mode)
            else:
                em = TPUForwardEmitter(1 if one_to_one else n_dests, 0,
                                       self.execution_mode)
            # keyed consumer fed by forward/broadcast: prefetch its key
            # column so a device-computed key never costs a sync D2H
            em.prefetch_field = getattr(first, "key_field", None)
            return em
        if getattr(first, "accepts_columns", False):
            # with_columns sink: whole column batches, no row boxing
            if not p_tpu:
                raise WindFlowError(
                    f"{first.name}: with_columns sink needs a device-plane "
                    "producer (CPU-plane edges deliver rows); drop "
                    "with_columns or move the producer to the device plane")
            if routing in (RoutingMode.KEYBY, RoutingMode.BROADCAST):
                raise WindFlowError(
                    f"{first.name}: with_columns sink supports forward/"
                    "rebalancing routing only (whole batches round-robin; "
                    "keyed distribution would need a device re-shard — "
                    "put the keyed operator before the sink)")
            from ..tpu.emitters_tpu import TPUColumnarExitEmitter
            return TPUColumnarExitEmitter(1 if one_to_one else n_dests,
                                          self.execution_mode)
        if routing is RoutingMode.KEYBY:
            # key_extractor is normalized to a callable by BasicOperator
            em: BasicEmitter = KeyByEmitter(first.key_extractor, n_dests,
                                            obs, self.execution_mode)
        elif routing is RoutingMode.BROADCAST:
            em = BroadcastEmitter(n_dests, obs, self.execution_mode)
        elif one_to_one:
            em = ForwardEmitter(1, obs, self.execution_mode)
        else:  # FORWARD shuffle / REBALANCING
            em = ForwardEmitter(n_dests, obs, self.execution_mode)
        if p_tpu and not c_tpu:  # device -> host exit
            from ..tpu.emitters_tpu import TPUExitEmitter
            return TPUExitEmitter(em)
        return em

    def _make_collector(self, stage: Stage, replica_idx: int):
        first_replica = stage.first_op.replicas[replica_idx]
        n_in = stage.channels[replica_idx].n_inputs
        if getattr(stage.first_op, "collector_override", None) == "id":
            # WLQ/REDUCE window stages sequence per-key result ids in every
            # execution mode (reference wf/multipipe.hpp:221-224)
            return IDSequencerCollector(n_in, first_replica,
                                        stage.first_op.key_extractor)
        separator = None
        if stage.first_op.op_type == OpType.JOIN:
            a_stages = getattr(stage, "join_a_stages", [])
            separator = sum(s.parallelism for s in a_stages)
        mode = self.execution_mode
        if mode is ExecutionMode.DEFAULT:
            from ..basic import JoinMode
            if (separator is not None
                    and getattr(stage.first_op, "join_mode", None)
                    is JoinMode.DP):
                # DP join replicas need an identical total order
                # (reference Join_Collector, wf/multipipe.hpp:216-220)
                return DPJoinCollector(n_in, first_replica, separator)
            if n_in > 1 or separator is not None:
                return WatermarkCollector(n_in, first_replica, separator)
            return None
        if mode is ExecutionMode.DETERMINISTIC:
            if n_in > 1 or separator is not None:
                return OrderingCollector(n_in, first_replica, separator,
                                         by_timestamp=True)
            return None
        # PROBABILISTIC: always reorder (disorder exists within one channel)
        return KSlackCollector(n_in, first_replica, self.dropped, separator)

    def _make_workers(self, stage: Stage) -> None:
        p = stage.parallelism
        rec_events = self._stage_flightrec_events(stage)
        from ..monitoring.flightrec import env_stall_sec
        stall = env_stall_sec()
        for i in range(p):
            chain: List[Any] = []
            channel = None
            if not stage.is_source:
                channel = stage.channels[i]
                # queue-occupancy/backpressure gauges: the consumer's
                # stats record reads its input channel live (Queue_*)
                stage.first_op.replicas[i].stats.input_channel = channel
                coll = self._make_collector(stage, i)
                if coll is not None:
                    chain.append(coll)
                    # restore path reaches the collector via its replica
                    stage.first_op.replicas[i]._collector = coll
            if stage.is_fused_tpu:
                # every sub-op aliases the same fused replica: the worker
                # chain holds it once
                chain.append(stage.first_op.replicas[i])
            else:
                chain.extend(op.replicas[i] for op in stage.ops)
            rec = None
            if rec_events > 0:
                from ..monitoring.flightrec import FlightRecorder
                rec = FlightRecorder(
                    rec_events, pid_label=stage.describe(),
                    tid_label=f"{self.name}/{stage.describe()}[{i}]")
                self._recorders.append(rec)
            w = Worker(f"{self.name}/{stage.describe()}[{i}]", chain, channel,
                       coordinator=self._coordinator, flightrec=rec)
            if rec is not None:
                w.on_crash = self._crash_dump
            if self._supervisor is not None:
                # supervised: a dying worker wakes the supervisor instead
                # of draining + forcing EOS (Worker.run error path)
                w.on_failure = self._supervisor.note_failure
            if stall > 0:
                w.force_idle_tick = True  # liveness ticks for the watchdog
            stage.workers.append(w)
            self._workers.append(w)

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def start(self, restore_from: Optional[str] = None) -> None:
        if self._started:
            raise WindFlowError("PipeGraph already started")
        self._validate()
        # supervision (with_supervision / WF_SUPERVISE=1): the supervisor
        # exists BEFORE _build so every worker gets its failure hook, and
        # checkpointing is enabled implicitly — a supervisor without a
        # checkpoint to restore can only resume from in-memory cursors
        if self._supervise_enabled:
            if not self._ckpt_enabled:
                self.with_checkpointing()
            from ..supervision.supervisor import Supervisor
            self._supervisor = Supervisor(self, self._supervise_policy)
            if self._device_probe is None:
                from ..supervision.health import probe_from_env
                self._device_probe = probe_from_env()
        # persistent compilation cache BEFORE any device program traces
        self._setup_compile_cache()
        if any(getattr(op, "is_tpu", False) for op in self._ops):
            # initialize the JAX backend on the MAIN thread: lazy first-touch
            # inside a worker thread can deadlock the PJRT client handshake
            import jax
            jax.devices()
        # checkpoint store/coordinator BEFORE _build: workers bind to the
        # coordinator at construction, and sources anchor their barrier
        # cursor to the restored epoch. SLO sampling too: replica
        # histograms allocate at replica construction
        self._ensure_slo_sampling()
        ckpt_dir, manifest = self._setup_checkpointing(restore_from)
        self._build()
        if ckpt_dir is not None:
            self._restore_replicas(ckpt_dir, manifest)
        if self._prewarm_enabled:
            # compile every bucketed chain signature BEFORE any source
            # opens: cold-start pays here, the stream never retraces
            self._prewarm_device_programs()
        if self._coordinator is not None:
            self._coordinator.expected_acks = len(self._workers)
            self._coordinator.worker_names = [w.name for w in self._workers]
            self._coordinator.diagnose = self._worker_diagnostics
            self._coordinator.start()
        self._started = True
        self._t0 = time.monotonic()
        # flight-recorder registry (feeds MonitoringServer's /trace) +
        # the stall watchdog (WF_STALL_SEC > 0, default off)
        from ..monitoring.flightrec import (StallWatchdog, env_stall_sec,
                                            register_graph)
        register_graph(self)
        stall = env_stall_sec()
        if stall > 0:
            self._watchdog = StallWatchdog(self, stall,
                                           dump_fn=self._stall_dump)
        if env_flag("WF_TRACING_ENABLED"):
            # reference: one MonitoringThread per PipeGraph when tracing
            # (wf/pipegraph.hpp:671-675)
            from ..monitoring.monitor import MonitoringThread
            self._monitor = MonitoringThread(self)
            self._monitor.start()
        if self._supervisor is not None:
            for w in self._workers:
                w.on_failure = self._supervisor.note_failure
            self._capture_initial_positions()
        for w in self._workers:
            w.start()
        if self._watchdog is not None:
            self._watchdog.start()
        if self._supervisor is not None:
            self._supervisor.start()
        # autoscaler policy thread (with_autoscaler / WF_AUTOSCALE=1)
        if self._autoscale_enabled or env_flag("WF_AUTOSCALE"):
            from ..scaling.autoscaler import Autoscaler
            self._autoscaler = Autoscaler(self, self._autoscale_policy)
            self._autoscaler.start()
        # overload governor (with_slo / WF_SLO_P99_MS): created after the
        # autoscaler so the SCALE rung can read its MAX_PAR and
        # synchronize cooldowns
        self._setup_overload_governor()
        if self._overload_governor is not None:
            self._overload_governor.start()

    def wait_end(self) -> None:
        if not self._started:
            raise WindFlowError("PipeGraph not started")
        if self._ended:
            return
        while True:
            # a live rescale (or supervised restart) REPLACES
            # self._workers mid-run: re-read the list after every join
            # sweep so we wait on the current plane
            workers = self._workers
            try:
                for w in workers:
                    w.join()
            except RuntimeError:
                # mid-rebuild: the new plane is published but its
                # threads are not started yet — come back around
                time.sleep(0.02)
                continue
            if self._workers is not workers:
                continue
            if self._rescaling or self._supervising:
                time.sleep(0.05)  # the new plane is coming
                continue
            sup = self._supervisor
            if sup is not None and sup.active \
                    and any(w.error is not None for w in workers):
                # a worker died but the supervisor has not reacted yet:
                # give it the chance (it restarts or escalates)
                time.sleep(0.02)
                continue
            break
        self._ended = True
        if self._supervisor is not None:
            self._supervisor.stop()
        if self._autoscaler is not None:
            self._autoscaler.stop()
        if self._overload_governor is not None:
            self._overload_governor.stop()
        self.elapsed_sec = time.monotonic() - self._t0
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._coordinator is not None:
            self._coordinator.stop()
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor.join(timeout=3)
        if self._supervisor is not None \
                and self._supervisor.escalated is not None:
            raise self._supervisor.escalated
        errors = [w.error for w in self._workers if w.error is not None]
        if not errors:
            # exactly-once sinks: the run finished cleanly, so every
            # still-pending epoch (the post-final-barrier tail, and any
            # epoch whose finalize landed after its sink worker exited)
            # commits now, in epoch order, on this thread. On the error
            # path they stay pending: restore rolls forward/aborts them.
            for op in self._ops:
                for r in {id(r): r for r in op.replicas}.values():
                    fin = getattr(r, "txn_complete", None)
                    if fin is not None:
                        fin()
        if errors:
            if len(errors) == 1:
                raise errors[0]
            # SEVERAL workers died: naming only errors[0] silently
            # discarded the rest — aggregate, naming every dead worker
            from ..basic import WorkerFailuresError
            raise WorkerFailuresError(
                {w.name: w.error for w in self._workers
                 if w.error is not None}) from errors[0]
        if env_flag("WF_TRACING_ENABLED"):
            self.dump_stats(os.environ.get("WF_LOG_DIR", "log"))

    def run(self, restore_from: Optional[str] = None) -> None:
        """Blocking run (reference ``PipeGraph::run``, L610).

        ``restore_from``: a checkpoint store root (resumes from the
        latest committed checkpoint) or one checkpoint directory. The
        topology must match the checkpointed one (same operator names
        and parallelisms); replayable sources resume from their recorded
        positions."""
        self.start(restore_from)
        self.wait_end()

    def _validate(self) -> None:
        if not self._stages:
            raise WindFlowError("empty PipeGraph: no sources")
        for s in self._stages:
            if s.is_split:
                missing = [b for b, st in enumerate(s.split_branches)
                           if st is None]
                if missing:
                    raise WindFlowError(
                        f"split after {s.describe()}: empty branches {missing}")
            elif s.downstream is None and not s.is_sink:
                raise WindFlowError(
                    f"stage {s.describe()} has no sink downstream")

    # ------------------------------------------------------------------
    # introspection (reference: getNumThreads, getNumDroppedTuples, stats)
    # ------------------------------------------------------------------
    def get_num_threads(self) -> int:
        self._build()
        return len(self._workers)

    def get_num_dropped_tuples(self) -> int:
        return self.dropped.value

    def get_stats(self) -> Dict[str, Any]:
        ops = []
        for op in self._ops:
            if getattr(op, "_fused_hidden", False):
                continue  # reported once under the fused stage's name
            fused_label = getattr(op, "_fused_stage_label", None)
            ops.append({
                "name": fused_label or op.name,
                "kind": ("Fused_TPU_Chain" if fused_label
                         else type(op).__name__),
                "parallelism": op.parallelism,
                "replicas": [r.stats.to_dict() for r in op.replicas],
            })
        # mark-final-then-drop: replicas a scale-down removed appear in
        # exactly ONE report with Final=true, then their series end
        finals, self._final_series = self._final_series, []
        ops.extend(finals)
        st = {
            "PipeGraph_name": self.name,
            "Mode": self.execution_mode.name,
            "Time_policy": self.time_policy.name,
            "Threads": len(self._workers),
            "Dropped_tuples": self.dropped.value,
            "Operators": ops,
        }
        if self._coordinator is not None:
            st["Checkpoints"] = self._coordinator.stats()
        if self._rescale_ctrl is not None:
            st["Rescales"] = self._rescale_ctrl.stats()
        if self._autoscaler is not None:
            st["Autoscaler"] = self._autoscaler.stats()
        if self._supervisor is not None:
            st["Supervision"] = self._supervisor.stats()
        if self._overload_governor is not None:
            st["Overload"] = self._overload_governor.stats()
        if self._prewarm_report is not None:
            st["Prewarm"] = self._prewarm_report
        if self._dlq is not None:
            st["Dead_letters"] = self._dlq.total
        # crash visibility: a worker that died no longer disappears
        # silently — its exception surfaces in the final report (the
        # replica-level Worker_last_error carries the full traceback)
        errs = {w.name: f"{type(w.error).__name__}: {w.error}"
                for w in self._workers if w.error is not None}
        if errs:
            st["Worker_errors"] = errs
        return st

    def dump_stats(self, log_dir: str = "log") -> str:
        """JSON stats + the dataflow diagram. The reference renders a PDF
        at wait_end and an SVG for the dashboard
        (``wf/pipegraph.hpp:525-534,732-734``); here the dot source and an
        SVG are always written (built-in layered renderer when no ``dot``
        binary exists) and a PDF additionally when Graphviz is present."""
        from ..monitoring.diagram import render_graphviz

        os.makedirs(log_dir, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in self.name) or "pipegraph"
        path = os.path.join(log_dir, f"{safe}_stats.json")
        with open(path, "w") as f:
            json.dump(self.get_stats(), f, indent=2)
        dot_src = self.to_dot()
        with open(os.path.join(log_dir, f"{safe}_diagram.dot"), "w") as f:
            f.write(dot_src + "\n")
        svg = render_graphviz(dot_src, "svg")
        with open(os.path.join(log_dir, f"{safe}_diagram.svg"), "wb") as f:
            f.write(svg if svg is not None else self.to_svg().encode())
        pdf = render_graphviz(dot_src, "pdf")
        if pdf is not None:
            with open(os.path.join(log_dir, f"{safe}_diagram.pdf"),
                      "wb") as f:
                f.write(pdf)
        return path

    # -- diagram (reference builds a Graphviz PDF/SVG) ---------------------
    def to_svg(self) -> str:
        """Dependency-free layered SVG of the stage DAG (the dashboard
        diagram; Graphviz output is preferred when a binary exists)."""
        from ..monitoring.diagram import stages_to_svg
        return stages_to_svg(self._stages, self.name)

    def to_dot(self) -> str:
        gname = self.name.replace('"', "'")
        lines = [f'digraph "{gname}" {{', "  rankdir=LR;",
                 "  node [shape=box, style=rounded];"]
        for s in self._stages:
            label = s.describe().replace('"', "'")
            par = "|".join(str(o.parallelism) for o in s.ops)
            extra = ""
            if s.chain_refused:
                # chain() fallback diagnostics: why this stage did not
                # fuse into its predecessor
                reason = s.chain_refused.replace('"', "'")
                extra = f"\\n[unchained: {reason}]"
            lines.append(f'  s{s.id} [label="{label}\\n({par}){extra}"];')
        for s in self._stages:
            for e in s.upstreams:
                style = ""
                if e.branch is not None:
                    style = f' [label="b{e.branch}"]'
                lines.append(f"  s{e.stage.id} -> s{s.id}{style};")
        lines.append("}")
        return "\n".join(lines)
