from .pipegraph import PipeGraph
from .multipipe import MultiPipe

__all__ = ["PipeGraph", "MultiPipe"]
