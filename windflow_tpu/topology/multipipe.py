"""MultiPipe: the linear-pipeline-with-shuffles builder.

Parity with ``wf/multipipe.hpp``:
- ``add`` / ``chain`` / ``add_sink`` / ``chain_sink`` (L952/1050);
- ``split(logic, n)`` + ``select(i)`` (L1178-1256);
- ``merge(*pipes)`` (via ``PipeGraph``, ``wf/pipegraph.hpp:265-460``).

A MultiPipe is a cursor over the PipeGraph's stage DAG: it tracks the open
tail stages that the next operator will consume from. After ``merge`` the
tail groups are remembered in order so a downstream Interval_Join can tell
stream A from stream B by input channel ranges (the reference uses a channel
``separator_id``, ``wf/watermark_collector.hpp:121-134``).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..basic import OpType, RoutingMode, WindFlowError
from ..operators.base import BasicOperator
from .stage import Stage, UpstreamEdge


class MultiPipe:
    def __init__(self, graph: "PipeGraph") -> None:  # noqa: F821
        self.graph = graph
        # open tails; normally one stage, several right after a merge
        self.tail_groups: List[List[Stage]] = []
        self.has_sink = False
        self.was_split = False
        self.was_merged = False
        self._split_children: List["MultiPipe"] = []
        self._parent_split: Optional[tuple] = None  # (stage, branch idx)

    # ------------------------------------------------------------------
    @property
    def _tails(self) -> List[Stage]:
        return [s for g in self.tail_groups for s in g]

    def _check_open(self, what: str) -> None:
        if self.has_sink:
            raise WindFlowError(f"cannot {what}: MultiPipe already has a sink")
        if self.was_split:
            raise WindFlowError(f"cannot {what}: MultiPipe was split; use select()")
        if not self.tail_groups and self._parent_split is None:
            raise WindFlowError(f"cannot {what}: empty MultiPipe")

    def _claim(self, op: BasicOperator) -> None:
        if op._used:
            raise WindFlowError(
                f"operator {op.name!r} was already added to a MultiPipe")
        op._used = True
        self.graph._register_op(op)

    # ------------------------------------------------------------------
    def add(self, op: BasicOperator) -> "MultiPipe":
        """New stage connected from all open tails (shuffle or one-to-one
        chosen at wiring time per the reference's Case 2/Case 3)."""
        self._check_open("add")
        subs = getattr(op, "sub_operators", None)
        if subs is not None:
            # composite operator (Paned/MapReduce windows): expand into
            # consecutive stages (the reference nests two Parallel_Windows
            # inside one operator; the runtime shape is identical)
            op._used = True
            for sub in subs:
                self.add(sub)
            return self
        self._claim(op)
        if op.op_type == OpType.JOIN and len(self.tail_groups) != 2:
            raise WindFlowError("Interval_Join must be added right after "
                                "merging exactly two MultiPipes")
        stage = Stage(op)
        if self._parent_split is not None and not self.tail_groups:
            # first operator of a split branch: connect to the parent stage
            ptail, branch = self._parent_split
            if ptail.split_branches[branch] is not None:
                raise WindFlowError("split branch already connected")
            ptail.split_branches[branch] = stage
            stage.upstreams.append(UpstreamEdge(ptail, branch))
        else:
            for group in self.tail_groups:
                for t in group:
                    if t.downstream is not None or t.is_split:
                        raise WindFlowError("tail stage already connected")
                    t.downstream = stage
                    stage.upstreams.append(UpstreamEdge(t, None))
        if op.op_type == OpType.JOIN:
            stage.join_a_stages = list(self.tail_groups[0])
        self.graph._stages.append(stage)
        self.tail_groups = [[stage]]
        self.was_merged = False
        if op.op_type == OpType.SINK:
            self.has_sink = True
        return self

    def chain(self, op: BasicOperator) -> "MultiPipe":
        """Fuse into the tail stage's thread (or, for consecutive device
        operators, its XLA program — ``topology/stage.py`` fusion rules)
        when legal, else fall back to ``add`` (reference behavior,
        ``wf/multipipe.hpp:1050-1100``). A refused chain records WHY on
        the fallback stage (``Stage.chain_refused``), surfaced by
        ``describe(diagnostics=True)`` and the dataflow diagram —
        silently degrading to a shuffle stage cost a PERF.md round to
        diagnose once."""
        self._check_open("chain")
        tails = self._tails
        if len(tails) == 1 and not self.was_merged:
            reason = tails[0].chain_refusal(op)
            if reason is None:
                self._claim(op)
                tails[0].chain(op)
                if op.op_type == OpType.SINK:
                    self.has_sink = True
                return self
        elif self.was_merged:
            reason = "chain after a merge needs a shuffle stage"
        elif not tails:
            reason = "first operator of a split branch starts its own stage"
        else:
            reason = "multiple open tails need a merging stage"
        self.add(op)
        for group in self.tail_groups:
            for stage in group:
                stage.chain_refused = reason
        return self

    def add_sink(self, op: BasicOperator) -> "MultiPipe":
        if op.op_type != OpType.SINK:
            raise WindFlowError("add_sink requires a Sink operator")
        return self.add(op)

    def chain_sink(self, op: BasicOperator) -> "MultiPipe":
        if op.op_type != OpType.SINK:
            raise WindFlowError("chain_sink requires a Sink operator")
        return self.chain(op)

    # ------------------------------------------------------------------
    def split(self, splitting_logic, n_branches: int) -> "MultiPipe":
        """Split the pipe into ``n_branches`` children; ``splitting_logic``
        maps a tuple to a branch index (or an iterable of indices, or None to
        drop). ``wf/multipipe.hpp:1178-1256``. A string names a tuple field
        holding the branch index — after a TPU operator this routes from one
        column D2H with no per-tuple Python (``split_gpu``,
        ``wf/multipipe.hpp:698-708``)."""
        self._check_open("split")
        if n_branches < 2:
            raise WindFlowError("split requires at least 2 branches")
        tails = self._tails
        if len(tails) != 1:
            raise WindFlowError("split right after a merge is not supported; "
                                "add an operator first")
        tail = tails[0]
        if tail.downstream is not None or tail.is_split:
            raise WindFlowError("tail stage already connected")
        tail.split_logic = splitting_logic
        tail.split_branches = [None] * n_branches
        self.was_split = True
        self._split_children = []
        for b in range(n_branches):
            child = MultiPipe(self.graph)
            child._parent_split = (tail, b)
            child.tail_groups = []  # filled by its first add()
            self._split_children.append(child)
        return self

    def select(self, branch: int) -> "MultiPipe":
        """Returns the MultiPipe of a split branch (``wf/multipipe.hpp``
        select)."""
        if not self.was_split:
            raise WindFlowError("select() requires a previous split()")
        if not (0 <= branch < len(self._split_children)):
            raise WindFlowError("select(): branch out of range")
        return self._split_children[branch]

    def get_split_branches(self) -> List["MultiPipe"]:
        if not self.was_split:
            raise WindFlowError("MultiPipe was not split")
        return list(self._split_children)

    # ------------------------------------------------------------------
    def merge(self, *others: "MultiPipe") -> "MultiPipe":
        """Merge this pipe with others into a new MultiPipe whose next
        operator consumes the union of the tails
        (``wf/pipegraph.hpp:265-460``)."""
        if not others:
            raise WindFlowError("merge requires at least one other MultiPipe")
        pipes = [self, *others]
        for p in pipes:
            p._check_open("merge")
            if p.graph is not self.graph:
                raise WindFlowError("cannot merge MultiPipes of different "
                                    "PipeGraphs")
        merged = MultiPipe(self.graph)
        merged.tail_groups = [list(p._tails) for p in pipes]
        merged.was_merged = True
        for p in pipes:
            p.tail_groups = []  # consumed
        return merged
