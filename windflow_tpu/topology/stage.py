"""Internal graph IR: Stage = one (possibly chained) operator group.

The reference builds its DAG as nested FastFlow all-to-alls ("matrioska",
``wf/multipipe.hpp:96-1329``); that encoding exists to satisfy FastFlow's
container types. Our runtime needs no such constraint, so the topology is a
plain DAG of stages; the *semantics* preserved from the reference are:

- Case 2 (same parallelism, FORWARD): one-to-one edges, order-preserving
  (``wf/multipipe.hpp:481-496``);
- Case 3 (shuffle): every producer replica connects to every consumer
  replica, with the emitter kind chosen by the consumer's routing
  (``wf/multipipe.hpp:497-531``, ``create_emitter`` L248-362);
- chaining fuses same-thread stages (``wf/multipipe.hpp:537-590``);
- the collector in front of each consumer replica is chosen by execution
  mode (``wf/multipipe.hpp:200-244``).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

from ..basic import ExecutionMode, OpType, RoutingMode, WindFlowError
from ..operators.base import BasicOperator


class UpstreamEdge:
    """Producer side of an edge into a stage."""

    __slots__ = ("stage", "branch")

    def __init__(self, stage: "Stage", branch: Optional[int]) -> None:
        self.stage = stage  # producer stage
        self.branch = branch  # split branch index on the producer, or None


class Stage:
    _next_id = 0

    def __init__(self, op: BasicOperator) -> None:
        self.id = Stage._next_id
        Stage._next_id += 1
        self.ops: List[BasicOperator] = [op]  # chained operators, in order
        self.upstreams: List[UpstreamEdge] = []
        self.downstream: Optional["Stage"] = None  # exclusive with split
        self.split_logic: Optional[Callable] = None
        self.split_branches: List[Optional["Stage"]] = []
        self.split_tpu = False  # split after a device-batch operator
        # runtime artifacts (filled at build time)
        self.channels: List[Any] = []  # one Channel per replica
        self.workers: List[Any] = []
        self.built = False

    # -- properties ---------------------------------------------------------
    @property
    def first_op(self) -> BasicOperator:
        return self.ops[0]

    @property
    def last_op(self) -> BasicOperator:
        return self.ops[-1]

    @property
    def parallelism(self) -> int:
        return self.ops[0].parallelism

    @property
    def is_source(self) -> bool:
        return self.first_op.op_type == OpType.SOURCE

    @property
    def is_sink(self) -> bool:
        return self.last_op.op_type == OpType.SINK

    @property
    def is_split(self) -> bool:
        return self.split_logic is not None

    def can_chain(self, op: BasicOperator) -> bool:
        """Reference chaining rule: FORWARD input, same parallelism, and the
        new operator must be chain-compatible (``wf/multipipe.hpp:537-590``,
        Reduce/windows excluded at 1058-1060)."""
        return (op.is_chainable
                and op.input_routing in (RoutingMode.FORWARD,)
                and op.parallelism == self.parallelism
                and not self.is_split
                and not self.is_sink
                and self.last_op.op_type not in (OpType.WIN, OpType.JOIN,
                                                 OpType.WIN_TPU, OpType.TPU))

    def chain(self, op: BasicOperator) -> None:
        self.ops.append(op)

    def describe(self) -> str:
        return "∘".join(o.name for o in self.ops)
