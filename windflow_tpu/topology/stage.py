"""Internal graph IR: Stage = one (possibly chained) operator group.

The reference builds its DAG as nested FastFlow all-to-alls ("matrioska",
``wf/multipipe.hpp:96-1329``); that encoding exists to satisfy FastFlow's
container types. Our runtime needs no such constraint, so the topology is a
plain DAG of stages; the *semantics* preserved from the reference are:

- Case 2 (same parallelism, FORWARD): one-to-one edges, order-preserving
  (``wf/multipipe.hpp:481-496``);
- Case 3 (shuffle): every producer replica connects to every consumer
  replica, with the emitter kind chosen by the consumer's routing
  (``wf/multipipe.hpp:497-531``, ``create_emitter`` L248-362);
- chaining fuses same-thread stages (``wf/multipipe.hpp:537-590``);
- the collector in front of each consumer replica is chosen by execution
  mode (``wf/multipipe.hpp:200-244``).
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional, Tuple

from ..basic import ExecutionMode, OpType, RoutingMode, WindFlowError
from ..operators.base import BasicOperator


def tpu_fusion_enabled() -> bool:
    """Device-chain fusion opt-out (``WF_TPU_FUSION=0`` falls back to
    today's per-stage wiring: one thread + one XLA program per device
    operator). Default on; read at chain() time so tests can A/B."""
    return os.environ.get("WF_TPU_FUSION", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def _keys_compatible(a: BasicOperator, b: BasicOperator) -> bool:
    """Two keyed device ops partition identically: same key field(s), or
    the very same extractor callable. Under equal parallelism the KEYBY
    re-shard between them is then the identity (same hash, same
    destination), so fusing drops the shuffle without changing which
    replica owns a key."""
    if a.key_field is not None or b.key_field is not None:
        return a.key_field == b.key_field
    if getattr(a, "key_fields", None) or getattr(b, "key_fields", None):
        return getattr(a, "key_fields", None) == getattr(b, "key_fields",
                                                         None)
    return a.key_extractor is b.key_extractor


class UpstreamEdge:
    """Producer side of an edge into a stage."""

    __slots__ = ("stage", "branch")

    def __init__(self, stage: "Stage", branch: Optional[int]) -> None:
        self.stage = stage  # producer stage
        self.branch = branch  # split branch index on the producer, or None


class Stage:
    _next_id = 0

    def __init__(self, op: BasicOperator) -> None:
        self.id = Stage._next_id
        Stage._next_id += 1
        self.ops: List[BasicOperator] = [op]  # chained operators, in order
        self.upstreams: List[UpstreamEdge] = []
        self.downstream: Optional["Stage"] = None  # exclusive with split
        self.split_logic: Optional[Callable] = None
        self.split_branches: List[Optional["Stage"]] = []
        self.split_tpu = False  # split after a device-batch operator
        # chain() fallback diagnostics: why this stage could not fuse
        # into its predecessor (None = it was never a chain candidate)
        self.chain_refused: Optional[str] = None
        # runtime artifacts (filled at build time)
        self.channels: List[Any] = []  # one Channel per replica
        self.workers: List[Any] = []
        self.built = False

    # -- properties ---------------------------------------------------------
    @property
    def first_op(self) -> BasicOperator:
        return self.ops[0]

    @property
    def last_op(self) -> BasicOperator:
        return self.ops[-1]

    @property
    def parallelism(self) -> int:
        return self.ops[0].parallelism

    @property
    def is_source(self) -> bool:
        return self.first_op.op_type == OpType.SOURCE

    @property
    def is_sink(self) -> bool:
        return self.last_op.op_type == OpType.SINK

    @property
    def is_split(self) -> bool:
        return self.split_logic is not None

    @property
    def is_fused_tpu(self) -> bool:
        """A chained stage whose operators are all device ops runs as ONE
        fused replica per slot (``tpu/fused_ops.py``) instead of a thread
        chain of inline-wired replicas."""
        return len(self.ops) > 1 and all(
            getattr(o, "is_tpu", False) for o in self.ops)

    def can_chain(self, op: BasicOperator) -> bool:
        return self.chain_refusal(op) is None

    def chain_refusal(self, op: BasicOperator) -> Optional[str]:
        """Why ``op`` cannot join this stage's thread/program — None when
        chaining is legal. CPU chaining follows the reference rule
        (FORWARD input, same parallelism, chain-compatible kind,
        ``wf/multipipe.hpp:537-590``, Reduce/windows excluded at
        1058-1060); device chaining follows the fusion legality rules
        (``_tpu_fusion_refusal``). The reason string is recorded on the
        fallback stage and surfaced by ``describe()`` / the diagram."""
        if self.is_split:
            return "tail stage was split"
        if self.is_sink:
            return "tail stage already ends in a sink"
        if op.parallelism != self.parallelism:
            return (f"mixed parallelism ({op.parallelism} vs "
                    f"{self.parallelism}) needs a re-shard between the "
                    "stages")
        tail_tpu = getattr(self.last_op, "is_tpu", False)
        cand_tpu = getattr(op, "is_tpu", False)
        if tail_tpu or cand_tpu:
            if not (tail_tpu and cand_tpu):
                return "device and host operators never share a stage"
            return self._tpu_fusion_refusal(op)
        if self.last_op.op_type in (OpType.WIN, OpType.JOIN,
                                    OpType.WIN_TPU):
            return (f"{self.last_op.name} ({self.last_op.op_type.value}) "
                    "terminates a chain")
        if op.input_routing not in (RoutingMode.FORWARD,):
            return (f"{op.input_routing.name} input routing needs its own "
                    "shuffle stage")
        if not op.is_chainable:
            return f"{op.name} is not chain-compatible"
        return None

    def _tpu_fusion_refusal(self, op: BasicOperator) -> Optional[str]:
        """Device-chain fusion legality: consecutive FORWARD (or
        key-compatible KEYBY) same-parallelism device transforms fuse
        into one XLA program; a terminator role (global or keyed
        Reduce_TPU, Ffat window) may END the chain — the keyed/window
        terminators additionally require their KEYBY shuffle to be the
        identity (single replica or a key-compatible keyed entry), and
        the window terminator a STATELESS prefix. Everything else keeps
        its own stage."""
        if not tpu_fusion_enabled():
            return "device-chain fusion disabled (WF_TPU_FUSION=0)"
        def _guarded(o):
            pol = getattr(o, "error_policy", None)
            return pol is not None and not pol.is_fail
        if _guarded(op) or any(_guarded(o) for o in self.ops):
            # poison isolation bisects a failing batch per OPERATOR; one
            # fused program cannot attribute the error to a sub-op
            return ("error policy set — poison-record bisection needs "
                    "the operator's own program boundary")
        last_role = getattr(self.last_op, "fusion_role", None)
        if last_role == "terminator":
            return (f"{self.last_op.name} (global Reduce_TPU) already "
                    "terminates the fused chain")
        if last_role == "keyed_terminator":
            return (f"{self.last_op.name} (keyed Reduce_TPU) already "
                    "terminates the fused chain")
        if last_role == "window_terminator":
            return (f"{self.last_op.name} is a window non-terminal "
                    "position — the window step already terminates the "
                    "fused chain (it changes the row domain: tuples -> "
                    "fired windows)")
        if any(getattr(o, "fusion_role", None) is None for o in self.ops):
            return (f"{self.first_op.name} has no composable device "
                    "kernel (mesh operators own their stage)")
        role = getattr(op, "fusion_role", None)
        if role is None:
            return (f"{op.name} has no composable device kernel "
                    "(mesh operators own their stage)")
        if role == "window_terminator":
            for o in self.ops:
                if getattr(o, "state_init", None) is not None:
                    # a window terminator's fused prefix runs TWICE per
                    # batch (prep-time mask + in-program compose), so a
                    # stateful prefix would double-advance its grid
                    return (f"{op.name} (window terminator) needs a "
                            f"stateless map/filter prefix — {o.name} "
                            "carries per-key device state")
        routing = op.input_routing
        if routing is RoutingMode.KEYBY:
            entry_keyed = (self.first_op.input_routing
                           is RoutingMode.KEYBY)
            if entry_keyed:
                if not _keys_compatible(self.first_op, op):
                    return (f"{op.name} keys differ from the chain "
                            "entry's — fusing would skip a real re-shard")
            elif role in ("keyed_terminator", "window_terminator"):
                # single-chip degeneration: with one replica the KEYBY
                # shuffle routes every key to the same destination, so
                # it reduces to the terminator's own in-program
                # sort/segment — no host keyby-emitter hop needed
                if self.parallelism != 1:
                    return (f"{op.name} needs a cross-device KEYBY "
                            f"shuffle (parallelism {self.parallelism}) — "
                            "the re-shard owns its own stage boundary")
            else:
                return (f"{op.name} is keyed but the chain entry "
                        f"({self.first_op.name}) is not — the KEYBY "
                        "shuffle needs its own stage boundary")
        elif routing is not RoutingMode.FORWARD:
            return (f"{routing.name} input routing needs its own shuffle "
                    "stage")
        return None

    def chain(self, op: BasicOperator) -> None:
        self.ops.append(op)

    def describe(self, diagnostics: bool = False) -> str:
        label = "∘".join(o.name for o in self.ops)
        if diagnostics and self.chain_refused:
            label += f" [unchained: {self.chain_refused}]"
        return label
