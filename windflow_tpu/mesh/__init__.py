"""Mesh execution plane: the keyed-state plane sharded across a device
mesh (``jax.sharding.Mesh``), as a first-class subsystem.

What lives here (absorbing the old ``parallel/mesh.py`` bolt-on):

- ``core``: the collective primitives — ``('key','data')`` mesh
  construction, the in-program bucket-by-owner + ``lax.all_to_all``
  KEYBY shuffle, the sharded FlatFAT forest, the flat-owner grid-scan
  and keyed-reduce step builders, and the jax compat seam
  (``wf_shard_map``/``pvary_fn``);
- ``ffat_mesh``: ``Ffat_Windows_Mesh`` — keyed sliding windows sharded
  over the mesh, with sharded snapshot/restore;
- ``ops_mesh``: ``Map_Mesh`` / ``Filter_Mesh`` / ``Reduce_Mesh`` — the
  mesh-sharded stateful Map/Filter (grid-scan key tables block-sharded
  along the slot axis) and keyed Reduce, built via ``.with_mesh(...)``
  on the TPU builders.

Every mesh operator runs ONE host replica driving every device: the
topology edge into it stays single-destination (the host KEYBY emitter
degenerates to staging), and the per-key routing happens inside the
jitted step as a device collective. Parallelism is the mesh shape, not
the replica count — ``rescale()`` refuses mesh ops; to change capacity,
checkpoint and restore with a different ``with_mesh(mesh_shape=...)``
(sharded restore relayouts the key axis, arXiv:2112.01075's
redistribution decomposition at slot-row granularity).

Import layering: ``import windflow_tpu.mesh`` stays jax-free; device
code imports lazily inside functions like the rest of the device plane.
"""

from __future__ import annotations

import os
import sys

DEFAULT_VIRTUAL_DEVICES = 8


def ensure_virtual_devices(n: int = DEFAULT_VIRTUAL_DEVICES) -> bool:
    """Force a virtual ``n``-device CPU platform so mesh programs compile
    and run without TPU hardware — the XLA_FLAGS dance every mesh script
    and test used to hand-roll, in one place. Must run BEFORE jax
    initializes (env flags are read at backend creation); returns False
    when jax is already imported (the caller should then check
    ``len(jax.devices())`` and skip if short)."""
    if "jax" in sys.modules:
        return False
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    return True


from .core import (MESH_AXES, default_ring_panes, make_key_mesh,  # noqa: E402
                   make_mesh_table, make_sharded_state, mesh_shard_count,
                   pvary_fn, ring_pane_window_query, sharded_ffat_forest,
                   sharded_grid_scan, sharded_keyby_window_step,
                   sharded_keyed_reduce, wf_shard_map)
from .ffat_mesh import Ffat_Windows_Mesh  # noqa: E402
from .ops_mesh import Filter_Mesh, Map_Mesh, Reduce_Mesh  # noqa: E402

__all__ = [
    "ensure_virtual_devices", "DEFAULT_VIRTUAL_DEVICES",
    "MESH_AXES", "default_ring_panes", "make_key_mesh", "make_mesh_table",
    "make_sharded_state", "mesh_shard_count", "pvary_fn",
    "ring_pane_window_query", "sharded_ffat_forest", "sharded_grid_scan",
    "sharded_keyby_window_step", "sharded_keyed_reduce", "wf_shard_map",
    "Ffat_Windows_Mesh", "Map_Mesh", "Filter_Mesh", "Reduce_Mesh",
]
