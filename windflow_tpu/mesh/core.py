"""Mesh execution plane, collective core: key-sharded streaming state
over a device mesh (promoted from ``parallel/mesh.py`` into the
``windflow_tpu.mesh`` subsystem).

The single-node reference has no distributed backend (SURVEY.md §5: FastFlow
shared-memory queues only). This module is the new surface: the keyby
shuffle — the core repartitioning primitive of the whole framework
(``wf/keyby_emitter*.hpp``) — expressed as XLA collectives over a
``jax.sharding.Mesh`` so keyed window state scales across chips:

- mesh axes ``('key', 'data')``: ingestion is data-parallel along ``data``
  (every chip stages its own micro-batches), keyed state is block-sharded
  along ``key`` (shard ``s`` owns keys ``[s*k_local, (s+1)*k_local)``, so
  global state row ``k`` is key ``k``);
- one jitted step per global batch, written with ``shard_map``:
  bucket-by-owner (local sort) -> ``lax.all_to_all`` along ``key`` (the
  ICI shuffle replacing the reference's lock-free queues) -> masked
  segment-sum into the local per-key pane accumulators -> ``psum`` along
  ``data`` to merge the data-parallel contributions -> global metrics via
  ``psum`` over both axes;
- collectives ride ICI: the all_to_all moves only tuple payloads, state
  never leaves its owner shard.

This is the dry-run surface validated on a virtual CPU mesh; the same
program runs unchanged on a real multi-chip TPU slice.
"""

from __future__ import annotations

import math
import threading

import numpy as np

from ..tpu.schema import broadcast_scalar_fields

# -- device-health exclusion registry ---------------------------------------
# Device ids the supervision plane has marked lost (health probe,
# supervision/health.py). Every mesh built through make_key_mesh avoids
# them, so a supervised rebuild after device loss lands the sharded state
# on the surviving devices. Process-global on purpose: a lost chip is
# lost for every graph in the process.
_EXCLUDED_DEVICE_IDS: frozenset = frozenset()
_EXCLUDE_LOCK = threading.Lock()


def set_excluded_devices(device_ids) -> None:
    """Replace the excluded-device set (ids as in ``device.id``). The
    supervisor calls this from the health probe before every rebuild;
    an empty set restores full capacity."""
    global _EXCLUDED_DEVICE_IDS
    with _EXCLUDE_LOCK:
        _EXCLUDED_DEVICE_IDS = frozenset(int(d) for d in device_ids)


def excluded_device_ids() -> frozenset:
    return _EXCLUDED_DEVICE_IDS


def healthy_devices():
    """``jax.devices()`` minus the excluded set. Falls back to ALL
    devices when the exclusion set would leave nothing — a probe gone
    mad must degrade to the pre-probe behavior, not to a zero-device
    mesh."""
    import jax

    devs = jax.devices()
    excl = _EXCLUDED_DEVICE_IDS
    if not excl:
        return list(devs)
    alive = [d for d in devs if d.id not in excl]
    return alive if alive else list(devs)


def wf_shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``shard_map`` across the jax generations this repo runs on: the
    stable ``jax.shard_map`` (``check_vma``) when it exists, else the
    ``jax.experimental.shard_map`` of the 0.4.x line (``check_rep`` —
    the same switch under its pre-rename name). One definition so every
    mesh program builds through the same compat seam."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        except TypeError:  # stable API before the check_rep rename
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)
    from jax.experimental.shard_map import shard_map as sm_exp
    return sm_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)


def pvary_fn(axes):
    """``lax.pcast(..., to="varying")`` when the running jax has the
    varying-axis type system; identity on older jax (whose shard_map
    rep-checking predates pcast — the call sites there run with
    ``check_vma=False``, where the cast is a no-op anyway)."""
    from jax import lax

    pc = getattr(lax, "pcast", None)
    if pc is not None:
        return lambda a: pc(a, axes, to="varying")
    return lambda a: a


def default_ring_panes(win_panes: int, slide_panes: int,
                       fire_rounds: int) -> int:
    """Default leaf-ring size: the smallest power of two holding the
    window PLUS the worst-case unfired backlog one step can leave
    (fire_rounds windows of slide panes each) — the single definition
    shared by the forest and the topology operator, so an all-defaults
    config always satisfies the forest's validation."""
    return 1 << max(3, math.ceil(
        math.log2(win_panes + max(fire_rounds * slide_panes, 16))))


def make_key_mesh(n_devices: int, shape=None):
    """Largest 2D ('key', 'data') mesh for n devices (data axis >= 1).
    ``shape=(ka, da)`` forces an explicit factorization (result invariance
    under mesh reshape is a correctness property — tests exercise 8x1 /
    4x2 / 2x4 over the same stream)."""
    import jax
    from jax.sharding import Mesh

    alive = healthy_devices()
    if shape is not None:
        ka, da = shape
        if ka * da > len(jax.devices()):
            raise ValueError(f"mesh shape {shape} needs {ka * da} devices, "
                             f"have {len(jax.devices())}")
        if ka * da > len(alive):
            # the forced factorization no longer fits the surviving
            # devices (health exclusions): degrade to the auto path over
            # what is healthy rather than refusing to recover
            return make_key_mesh(len(alive))
        arr = np.array(alive[:ka * da]).reshape(ka, da)
        return Mesh(arr, ("key", "data"))
    n_devices = max(1, min(int(n_devices), len(alive)))
    devs = alive[:n_devices]
    ka = n_devices
    da = 1
    # prefer a 2D mesh when the device count allows it
    for cand in (2, 4):
        if n_devices % cand == 0 and n_devices // cand >= 2:
            da = cand
            ka = n_devices // cand
            break
    arr = np.array(devs).reshape(ka, da)
    return Mesh(arr, ("key", "data"))


def make_sharded_state(mesh, n_keys: int, n_panes: int):
    """Per-key pane accumulators sharded along the 'key' axis (replicated
    along 'data'); zeros-initialized."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    ka = mesh.shape["key"]
    n_keys_padded = math.ceil(n_keys / ka) * ka
    state = jnp.zeros((n_keys_padded, n_panes), jnp.float32)
    counts = jnp.zeros((n_keys_padded, n_panes), jnp.int32)
    sharding = NamedSharding(mesh, P("key", None))
    return (jax.device_put(state, sharding),
            jax.device_put(counts, sharding))


def _route_to_owners(ka: int, k_local: int, C: int, keys, panes, vals):
    """The ICI keyby shuffle shared by the sharded steps: bucket local
    tuples by owner shard (stable sort + run positions, capacity-masked),
    ``lax.all_to_all`` along 'key', and recover (keys, panes, vals pytree,
    valid mask, local key index) on the owner. Runs inside shard_map."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    tmap = jax.tree_util.tree_map
    B = keys.shape[0]
    # key < 0 marks a PADDING lane (partial input batches): route it to
    # shard 0 — it arrives with key -1, fails the ``valid`` mask, and is
    # dropped. clip (not minimum) so the negative key cannot produce a
    # negative destination (negative scatter indices would WRAP, not drop)
    dest = jnp.clip(keys // k_local, 0, ka - 1).astype(jnp.int32)
    order = jnp.argsort(dest, stable=True)
    dsort, ksort, psort = dest[order], keys[order], panes[order]
    vsort = tmap(lambda a: a[order], vals)
    # position of each tuple within its destination run
    start_of_dest = jnp.searchsorted(dsort, jnp.arange(ka))
    within = jnp.arange(B) - start_of_dest[dsort]
    ok = within < C
    flat = dsort * C + jnp.minimum(within, C - 1)

    def bucketize(col, fill):
        buf = jnp.full((ka * C,), fill, dtype=col.dtype)
        return buf.at[flat].set(
            jnp.where(ok, col, fill), mode="drop").reshape(ka, C)

    # the ICI shuffle: block i of every chip goes to key-shard i
    a2a = lambda b: lax.all_to_all(b, "key", 0, 0, tiled=True).reshape(-1)
    rk = a2a(bucketize(ksort, -1))
    rp = a2a(bucketize(psort, 0))
    rv = tmap(lambda a: a2a(bucketize(a, np.zeros((), a.dtype)[()])), vsort)
    valid = rk >= 0
    shard = lax.axis_index("key")
    local_key = jnp.where(valid, rk - shard * k_local, 0).astype(jnp.int32)
    return rk, rp, rv, valid, local_key


def sharded_keyby_window_step(mesh, n_keys: int, n_panes: int,
                              local_batch: int):
    """Builds the jitted global step: (state, counts, keys, values, panes)
    -> (state', counts', global_tuple_count).

    ``keys``/``values``/``panes`` are global arrays of shape
    (ka*da*local_batch,) sharded over both mesh axes; the step re-shards
    tuples to their key-owner chips with all_to_all and folds them into the
    owner's pane accumulators.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    ka = mesh.shape["key"]
    da = mesh.shape["data"]
    n_keys_padded = math.ceil(n_keys / ka) * ka
    k_local = n_keys_padded // ka
    # per-destination bucket capacity: worst case all local tuples go to one
    # owner; pad to local_batch (masked)
    C = local_batch

    def local_step(state, counts, keys, values, panes):
        # state/counts: (k_local, n_panes); keys/values/panes: (B,)
        # BLOCK key ownership: shard s owns global keys
        # [s*k_local, (s+1)*k_local), so returned global row k IS key k
        rk, rp, rv, valid, local_key = _route_to_owners(
            ka, k_local, C, keys, panes, {"v": values})
        rv = rv["v"]
        pane_idx = jnp.where(valid, rp % n_panes, 0).astype(jnp.int32)
        flat_idx = jnp.where(valid, local_key * n_panes + pane_idx,
                             k_local * n_panes)
        # accumulate the DELTA only, then merge deltas across the
        # data-parallel replicas — psum of state+delta would multiply the
        # pre-existing accumulators by the data-axis size every step
        delta = jnp.zeros(k_local * n_panes, state.dtype).at[flat_idx].add(
            jnp.where(valid, rv, 0), mode="drop").reshape(k_local, n_panes)
        dcount = jnp.zeros(k_local * n_panes, counts.dtype).at[flat_idx].add(
            jnp.where(valid, 1, 0), mode="drop").reshape(k_local, n_panes)
        state = state + lax.psum(delta, "data")
        counts = counts + lax.psum(dcount, "data")
        n_tuples = lax.psum(jnp.sum(valid), ("key", "data"))
        return state, counts, n_tuples

    stepped = wf_shard_map(
        local_step, mesh=mesh,
        in_specs=(P("key", None), P("key", None),
                  P(("key", "data")), P(("key", "data")), P(("key", "data"))),
        out_specs=(P("key", None), P("key", None), P()),
    )
    return jax.jit(stepped), n_keys_padded, ka * da * local_batch


def sharded_ffat_forest(mesh, lift, combine, n_keys: int, win_panes: int,
                        slide_panes: int, local_batch: int,
                        fire_rounds: int = 2, ring_panes: int = 0,
                        late_policy: str = "keep_open"):
    """The FLAGSHIP operator sharded over the mesh: a FlatFAT forest whose
    key axis is block-sharded along ``'key'`` (shard s owns keys
    [s*k_local, (s+1)*k_local)), with ingestion data-parallel along
    ``'data'``.

    Multi-chip redesign of ``tpu/ffat_tpu.py`` (single-chip keeps its
    host-metadata control plane; here the per-key control state —
    next_fire/max_leaf — lives ON DEVICE in the shard that owns the key,
    so firing needs no host round-trip and no cross-chip metadata):

      bucket-by-owner -> ``lax.all_to_all`` along 'key' (tuple payloads
      ride ICI; forest state never moves) -> per-shard segmented scan +
      leaf scatter-combine -> per-shard level rebuild -> ``fire_rounds``
      device-side fire rounds (every owned key fires its next window when
      the frontier passed it; queries are the same <=2 log F ring walks,
      vmapped over the shard's keys) -> per-round leaf eviction.

    Returns ``(init_fn, step_fn, meta)``:
    - ``init_fn(sample_vals) -> state`` — 5-tuple (trees, tvalid,
      next_fire, max_leaf, fired), properly sharded; ``sample_vals`` is a
      pytree of (1,)-arrays carrying the RAW tuple column dtypes
      (pre-lift);
    - ``step_fn(*state, keys, values, panes, frontier)`` (state is
      SPLATTED) -> flat 10-tuple ``(trees, tvalid, next_fire, max_leaf,
      fired, results, res_valid, res_wid, n_tuples, n_late)``; results
      have shape (K_pad, fire_rounds) per lift field — window aggregates
      for each owned key, up to ``fire_rounds`` windows per step;
      ``n_late`` counts tuples dropped by the per-key lateness rule —
      under ``late_policy="keep_open"`` (default) a pane is late iff
      EVERY window containing it has fired (pane < next_fire[key]); under
      ``late_policy="ref_fired"`` it is the reference's exact bound
      (``wf/window_replica.hpp:257-258``): late iff it falls anywhere
      inside the key's last FIRED window (pane < next_fire + win - slide
      once a window fired), i.e. the reference also drops tuples that
      still belong to OPEN windows;
    - ``meta = (K_pad, k_local, global_batch)``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    ka = mesh.shape["key"]
    da = mesh.shape["data"]
    if da & (da - 1):
        raise ValueError(f"sharded_ffat_forest: the 'data' axis must be a "
                         f"power of two for the delta-merge butterfly "
                         f"(got {da})")
    K_pad = math.ceil(n_keys / ka) * ka
    k_local = K_pad // ka
    F = ring_panes or default_ring_panes(win_panes, slide_panes,
                                         fire_rounds)
    if F & (F - 1) or F < win_panes + fire_rounds * slide_panes:
        raise ValueError(
            f"sharded_ffat_forest: ring_panes must be a power of two >= "
            f"win_panes + fire_rounds*slide_panes (got F={F}, "
            f"win={win_panes}, rounds={fire_rounds}, slide={slide_panes})")
    # int32 index-plane guard: the scatter uses flat indices up to
    # k_local*2F (lkey*2F + F + leaf); ring GROWTH doubles F through this
    # same construction path, so a large key_capacity times a grown ring
    # must refuse loudly here rather than wrap int32 silently
    if k_local * 2 * F > np.iinfo(np.int32).max:
        raise ValueError(
            f"sharded_ffat_forest: k_local*2*ring_panes = {k_local * 2 * F}"
            f" overflows the int32 index plane (k_local={k_local}, "
            f"ring_panes={F}); shard over more 'key' devices or lower "
            f"key_capacity/ring_panes")
    if late_policy not in ("keep_open", "ref_fired"):
        raise ValueError(
            f"sharded_ffat_forest: late_policy must be 'keep_open' or "
            f"'ref_fired' (got {late_policy!r})")
    # static late-bound offset: 0 keeps tuples that still belong to open
    # windows; win-slide reproduces the reference's fired-window bound
    # (gated below on next_fire > 0 == "at least one window fired/skipped",
    # matching the reference's last_lwid >= 0 gate). Dropping MORE tuples
    # is always ring-safe (fewer leaf touches); the offset must never go
    # NEGATIVE (hopping windows, slide > win: a bound below next_fire
    # would admit tuples whose leaf slot is already evicted). Clamping to
    # 0 loses nothing there — panes in [nf+win-slide, nf) fall in the
    # gaps BETWEEN hopping windows and contribute to no window at all,
    # so the two policies coincide for hopping windows.
    LATE_OFF = max(0, win_panes - slide_panes) \
        if late_policy == "ref_fired" else 0
    NNODES = 2 * F
    LOGQ = NNODES.bit_length()
    C = local_batch  # per-destination bucket capacity (masked)
    tmap = jax.tree_util.tree_map

    def comb_valid(va, a, vb, b):
        both = va & vb
        merged = combine(a, b)
        out = tmap(lambda m, x, y: jnp.where(both, m, jnp.where(va, x, y)),
                   merged, a, b)
        return va | vb, out

    def range_query(tree_row, vrow, lo, length):
        # loop-carry scalars must carry the shard_map varying axes
        pv = pvary_fn(("key", "data"))
        zero = tmap(lambda a: pv(jnp.zeros((), a.dtype)), tree_row)

        def body(_, st):
            l, r, lv, la, rv, ra = st
            take_l = ((l & 1) == 1) & (l < r)
            il = jnp.clip(l, 0, NNODES - 1)
            node_l = tmap(lambda a: a[il], tree_row)
            lv, la = comb_valid(lv, la, vrow[il] & take_l, node_l)
            l = jnp.where(take_l, l + 1, l)
            take_r = ((r & 1) == 1) & (l < r)
            ir = jnp.clip(r - 1, 0, NNODES - 1)
            node_r = tmap(lambda a: a[ir], tree_row)
            rv, ra = comb_valid(vrow[ir] & take_r, node_r, rv, ra)
            r = jnp.where(take_r, r - 1, r)
            return (l >> 1, r >> 1, lv, la, rv, ra)

        init = (lo + F, lo + length + F,
                pv(jnp.zeros((), bool)), zero, pv(jnp.zeros((), bool)), zero)
        st = lax.fori_loop(0, LOGQ, body, init)
        return comb_valid(st[2], st[3], st[4], st[5])

    def window_query(tree_row, vrow, start_phys, length):
        len1 = jnp.minimum(length, F - start_phys)
        v1, r1 = range_query(tree_row, vrow, start_phys, len1)
        v2, r2 = range_query(tree_row, vrow, jnp.zeros_like(start_phys),
                             length - len1)
        return comb_valid(v1, r1, v2, r2)

    def local_step(trees, tvalid, next_fire, max_leaf, fired,
                   keys, raw_vals, panes, frontier):
        # ---- fast-forward DRAINED keys past the frontier ----------------
        # A key with max_leaf < next_fire holds no live leaves (everything
        # below next_fire is evicted) and its pending windows are provably
        # empty — but while it sits idle the frontier keeps moving, and on
        # resume a new pane p >= next_fire + F would alias the ring slots
        # its stalled windows still read: they would fire valid=True with
        # the NEW tuple's value, and the per-round eviction would destroy
        # the new leaf before its real window fires. Jump next_fire to the
        # first slide-aligned start that is not yet fireable (skipping
        # only empty windows); ``fired`` tracks next_fire//slide (origin
        # numbering) and jumps with it. This makes the host's ring-headroom
        # floor a real invariant for idle-resume keys.
        first_unfireable = jnp.maximum(
            jnp.int32(0),
            ((frontier - win_panes) // slide_panes + 1) * slide_panes
        ).astype(jnp.int32)
        ff = (max_leaf < next_fire) & (next_fire < first_unfireable)
        next_fire = jnp.where(ff, first_unfireable, next_fire)
        fired = jnp.where(ff, first_unfireable // slide_panes, fired)

        # ---- route tuples to their key-owner shard (ICI all_to_all) ----
        recv_k, recv_p, recv_v, valid, lkey = _route_to_owners(
            ka, k_local, C, keys, panes, raw_vals)
        # per-key lateness rule. Default ("keep_open", LATE_OFF=0): a pane
        # is late iff EVERY window containing it has fired (p < next_fire)
        # — a deliberate LESS-LOSSY divergence from the reference, which
        # also drops tuples inside the last fired window even when they
        # still belong to open windows (``wf/window_replica.hpp:257-258``:
        # index < win + last_lwid*slide, gated on last_lwid >= 0).
        # "ref_fired" reproduces that bound exactly: next_fire > 0 means
        # at least one window fired (or was skipped provably-empty, which
        # the reference fires too), i.e. the last fired window ends at
        # next_fire + win - slide. Late panes must also not touch the
        # forest — their leaf slot may alias an evicted ring position.
        # Counted and returned so the host can account drops.
        nf_t = next_fire[lkey]
        late_bound = nf_t
        if LATE_OFF:
            late_bound = nf_t + jnp.where(nf_t > 0, jnp.int32(LATE_OFF), 0)
        late = valid & (recv_p < late_bound)
        valid = valid & ~late
        n_late = lax.psum(jnp.sum(late), ("key", "data"))

        # ---- segmented scan by (key, pane) + leaf scatter-combine ------
        vals = broadcast_scalar_fields(lift(recv_v), recv_k.shape[0])
        leaf = jnp.where(valid, recv_p % F, 0).astype(jnp.int32)
        big = jnp.int32(k_local * F)
        composite = jnp.where(valid, lkey * F + leaf, big)
        order2 = jnp.argsort(composite, stable=True)
        sc = composite[order2]
        same_prev = jnp.concatenate([jnp.zeros((1,), bool), sc[1:] == sc[:-1]])
        is_end = jnp.concatenate(
            [sc[1:] != sc[:-1], jnp.ones((1,), bool)]) & (sc < big)
        svals = tmap(lambda a: a[order2], vals)

        def seg_op(a, b):
            fa, sa = a
            fb, same_b = b
            merged = combine(fa, fb)
            out = tmap(lambda m, y: jnp.where(same_b, m, y), merged, fb)
            return out, sa & same_b

        scanned, _ = lax.associative_scan(seg_op, (svals, same_prev))
        flat_idx = (lkey[order2] * NNODES + F + leaf[order2])
        OOB = k_local * NNODES
        safe_idx = jnp.where(is_end, flat_idx, OOB)
        # scatter segment tails into a DELTA forest first: the state is
        # replicated along 'data' while each data replica received a
        # DISJOINT tuple subset, so deltas must merge across 'data'
        # (butterfly ppermute with the user combine — a generic-combine
        # all_reduce; cross-replica combine order is arbitrary, the same
        # guarantee DEFAULT mode gives multi-replica CPU ingestion)
        dleaf = tmap(lambda sv: jnp.zeros(
            (k_local * NNODES,), sv.dtype).at[safe_idx].set(
            sv, mode="drop"), scanned)
        dvalid = jnp.zeros((k_local * NNODES,), bool).at[safe_idx].set(
            is_end, mode="drop")
        shift = 1
        while shift < da:
            perm = [(i, i ^ shift) for i in range(da)]
            p_leaf = tmap(lambda a: lax.ppermute(a, "data", perm), dleaf)
            p_valid = lax.ppermute(dvalid, "data", perm)
            dvalid, dleaf = comb_valid(dvalid, dleaf, p_valid, p_leaf)
            shift <<= 1
        # combine the merged delta into the state leaves
        leaf_valid = tvalid.reshape(-1) & dvalid
        merged_all = combine(tmap(lambda t: t.reshape(-1), trees), dleaf)
        trees = tmap(lambda t, m, dl: jnp.where(
            dvalid, jnp.where(leaf_valid, m, dl), t.reshape(-1)
        ).reshape(t.shape), trees, merged_all, dleaf)
        tvalid = (tvalid.reshape(-1) | dvalid).reshape(tvalid.shape)
        # per-key max pane (control state stays on the owner shard),
        # merged across the data replicas
        max_leaf = max_leaf.at[lkey].max(
            jnp.where(valid, recv_p, -1).astype(max_leaf.dtype))
        max_leaf = lax.pmax(max_leaf, "data")

        # ---- level rebuild across the shard's forest -------------------
        # SKIPPED (lax.cond) when no owned key can fire this step: the
        # mesh rebuilds from leaves in-step, so internal nodes are only
        # ever read by this step's own fire rounds — a non-firing step
        # leaves them stale with no reader, and the next firing step's
        # cond takes the rebuild branch. The rebuild is O(keys × ring)
        # regardless of batch size: the dominant per-step term under
        # periodic (sparse) watermarks.
        def _rebuild(carry):
            trees, tvalid = carry
            lvl = F >> 1
            while lvl >= 1:
                lc = tmap(lambda t: t[:, 2 * lvl:4 * lvl:2], trees)
                rc = tmap(lambda t: t[:, 2 * lvl + 1:4 * lvl:2], trees)
                vlc = tvalid[:, 2 * lvl:4 * lvl:2]
                vrc = tvalid[:, 2 * lvl + 1:4 * lvl:2]
                merged = combine(lc, rc)
                node = tmap(lambda m, a, b: jnp.where(
                    vlc & vrc, m, jnp.where(vlc, a, b)), merged, lc, rc)
                trees = tmap(lambda t, nd: t.at[:, lvl:2 * lvl].set(nd),
                             trees, node)
                tvalid = tvalid.at[:, lvl:2 * lvl].set(vlc | vrc)
                lvl >>= 1
            return trees, tvalid

        any_elig = jnp.any((next_fire + win_panes <= frontier)
                           & (max_leaf >= next_fire))
        trees, tvalid = lax.cond(any_elig, _rebuild, lambda c: c,
                                 (trees, tvalid))

        # ---- device-side fire rounds -----------------------------------
        pv = pvary_fn(("key", "data"))
        res = tmap(lambda a: pv(jnp.zeros((k_local, fire_rounds), a.dtype)),
                   vals)
        res_valid = pv(jnp.zeros((k_local, fire_rounds), bool))
        res_wid = pv(jnp.zeros((k_local, fire_rounds), jnp.int32))

        def round_body(r, st):
            trees, tvalid, next_fire, max_leaf, fired, res, rvalid, rwid = st
            eligible = ((next_fire + win_panes <= frontier)
                        & (max_leaf >= next_fire))
            start = next_fire
            length = jnp.where(
                eligible,
                jnp.minimum(win_panes, max_leaf + 1 - start), 0
            ).astype(jnp.int32)
            qv, qr = jax.vmap(window_query)(
                trees, tvalid, (start % F).astype(jnp.int32), length)
            qv = qv & eligible
            res = tmap(lambda acc, q: acc.at[:, r].set(
                jnp.where(qv, q, acc[:, r])), res, qr)
            rvalid = rvalid.at[:, r].set(qv)
            rwid = rwid.at[:, r].set(
                jnp.where(eligible, fired, -1).astype(jnp.int32))
            # evict the panes sliding out of every fired key
            ev = start[:, None] + jnp.arange(slide_panes)[None, :]
            ev_ok = eligible[:, None] & (ev <= max_leaf[:, None])
            rows = jnp.broadcast_to(
                jnp.arange(k_local)[:, None], ev.shape)
            eflat = jnp.where(ev_ok, rows * NNODES + F + ev % F,
                              k_local * NNODES)
            tvalid = tvalid.reshape(-1).at[eflat.reshape(-1)].set(
                False, mode="drop").reshape(tvalid.shape)
            next_fire = jnp.where(eligible, next_fire + slide_panes,
                                  next_fire)
            fired = jnp.where(eligible, fired + 1, fired)
            return (trees, tvalid, next_fire, max_leaf, fired,
                    res, rvalid, rwid)

        (trees, tvalid, next_fire, max_leaf, fired, res, res_valid,
         res_wid) = lax.fori_loop(
            0, fire_rounds, round_body,
            (trees, tvalid, next_fire, max_leaf, fired, res, res_valid,
             res_wid))
        n_tuples = lax.psum(jnp.sum(valid), ("key", "data"))
        return (trees, tvalid, next_fire, max_leaf, fired,
                res, res_valid, res_wid, n_tuples, n_late)

    def init_fn(sample_vals):
        """sample_vals: pytree of (1,) arrays with the RAW tuple column
        dtypes (pre-lift); returns the sharded state pytree."""
        shapes = jax.eval_shape(
            lambda v: broadcast_scalar_fields(lift(v), 1), sample_vals)
        sh_keys = NamedSharding(mesh, P("key", None))
        sh_key1 = NamedSharding(mesh, P("key"))
        trees = {name: jax.device_put(jnp.zeros((K_pad, NNODES), s.dtype),
                                      sh_keys)
                 for name, s in shapes.items()}
        tvalid = jax.device_put(jnp.zeros((K_pad, NNODES), bool), sh_keys)
        next_fire = jax.device_put(jnp.zeros((K_pad,), jnp.int32), sh_key1)
        max_leaf = jax.device_put(jnp.full((K_pad,), -1, jnp.int32), sh_key1)
        fired = jax.device_put(jnp.zeros((K_pad,), jnp.int32), sh_key1)
        return trees, tvalid, next_fire, max_leaf, fired

    stepped = wf_shard_map(
        local_step, mesh=mesh,
        in_specs=(P("key", None), P("key", None), P("key"), P("key"),
                  P("key"),
                  P(("key", "data")), P(("key", "data")), P(("key", "data")),
                  P()),
        out_specs=(P("key", None), P("key", None), P("key"), P("key"),
                   P("key"),
                   P("key", None), P("key", None), P("key", None), P(),
                   P()),
        # the butterfly delta-merge makes state/results equal across the
        # 'data' axis, but the varying-axis type system cannot infer that
        # replication through a generic-combine reduction
        check_vma=False,
    )
    return init_fn, jax.jit(stepped), (K_pad, k_local, ka * da * local_batch)


def ring_pane_window_query(mesh, n_panes_global: int, win_panes: int,
                           slide_panes: int):
    """Sliding-window combines over a PANE-SHARDED timeline — the
    long-context analog: when one chip cannot hold a window's pane state
    (SURVEY.md §5: pane decomposition / window partitioning is how the
    reference scales window length), the pane axis itself is sharded over
    the mesh's 'key' axis; a shard owns the windows STARTING in its slice,
    which extend up to win-1 panes into the RIGHT neighbor, so each shard
    receives the head of its right neighbor via a RING exchange
    (``lax.ppermute`` over ICI), not a full all_gather.

    Builds a jitted fn: (pane_partials[P_global]) -> window_sums[W_global]
    where window w = sum of panes [w*slide, w*slide+win). Collectives move
    exactly the overlap, O(win) per link, independent of timeline length.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n_shards = mesh.shape["key"]
    if n_panes_global % n_shards:
        raise ValueError("n_panes_global must divide the key axis")
    p_local = n_panes_global // n_shards
    halo = win_panes - 1
    if halo > p_local:
        raise ValueError("window span exceeds one shard + halo; increase "
                         "panes per shard")
    n_windows = (n_panes_global - win_panes) // slide_panes + 1

    def local(panes):
        # panes: (p_local,) this shard's slice of the timeline. A shard
        # owns the windows STARTING in its slice; those extend up to
        # win-1 panes into the RIGHT neighbor, so the halo is the right
        # neighbor's head (ring ppermute: shard i sends its head to i-1).
        perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]
        right_head = lax.ppermute(panes[:halo], "key", perm) \
            if halo > 0 else jnp.zeros((0,), panes.dtype)
        shard = lax.axis_index("key")
        ext = jnp.concatenate([panes, right_head])  # (p_local + halo,)
        start0_global = shard * p_local
        first_w = (start0_global + slide_panes - 1) // slide_panes
        max_w_here = p_local // slide_panes + 1
        w_ids = first_w + jnp.arange(max_w_here)
        starts_local = w_ids * slide_panes - start0_global
        valid = (w_ids < n_windows) & (starts_local < p_local)
        idx = jnp.clip(starts_local[:, None]
                       + jnp.arange(win_panes)[None, :],
                       0, p_local + halo - 1)
        sums = jnp.where(valid[:, None], ext[idx], 0).sum(axis=1)
        # each window is produced by exactly one shard; psum assembles the
        # dense global window vector
        out = jnp.zeros((n_windows,), panes.dtype)
        out = out.at[jnp.clip(w_ids, 0, n_windows - 1)].add(
            jnp.where(valid, sums, 0))
        return lax.psum(out, "key")

    stepped = wf_shard_map(local, mesh=mesh,
                           in_specs=(P("key"),), out_specs=P())
    return jax.jit(stepped), n_windows


# ---------------------------------------------------------------------------
# flat-owner routing: the keyed-plane shuffle for the sharded operators
# ---------------------------------------------------------------------------
# The FFAT plane block-shards keys along the 'key' axis only and merges the
# data-parallel contributions with an associative butterfly. A grid-scan
# state transition is SEQUENTIAL per key (func(row, state) is arbitrary),
# so no cross-replica merge exists: every tuple of a key must land on ONE
# device. The sharded Map/Filter/Reduce therefore block-shard the slot
# space over the FLATTENED ('key', 'data') device order (the same
# slot // k_local owner formula, ns = ka*da shards), and the all_to_all
# runs over the axis tuple — the mesh shape stays a pure layout choice,
# which is exactly what makes 8x1 / 4x2 / 2x4 results identical.

MESH_AXES = ("key", "data")


def _route_flat(ns: int, k_local: int, C: int, slots, aux, vals):
    """Bucket-by-owner + ``lax.all_to_all`` over the flattened mesh: the
    in-program KEYBY shuffle of the sharded operators. ``slots`` are
    dense key slots (< 0 = padding lane, routed to shard 0 and dropped by
    the ``valid`` mask); ``aux`` is one extra int column that rides the
    shuffle (global arrival position for scans, unused for reduce);
    ``vals`` a pytree of 1-D columns. Returns
    ``(recv_slots, recv_aux, recv_vals, valid, local_key, order, flat,
    ok)`` — the last three are the source-side routing map
    ``_route_back`` needs to return per-row results to arrival order."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    tmap = jax.tree_util.tree_map
    B = slots.shape[0]
    dest = jnp.clip(slots // k_local, 0, ns - 1).astype(jnp.int32)
    order = jnp.argsort(dest, stable=True)
    dsort, ssort, asort = dest[order], slots[order], aux[order]
    vsort = tmap(lambda a: a[order], vals)
    start_of_dest = jnp.searchsorted(dsort, jnp.arange(ns))
    within = jnp.arange(B) - start_of_dest[dsort]
    ok = within < C
    flat = dsort * C + jnp.minimum(within, C - 1)

    def bucketize(col, fill):
        buf = jnp.full((ns * C,), fill, dtype=col.dtype)
        return buf.at[flat].set(
            jnp.where(ok, col, fill), mode="drop").reshape(ns, C)

    a2a = lambda b: lax.all_to_all(b, MESH_AXES, 0, 0, tiled=True).reshape(-1)
    rs = a2a(bucketize(ssort, jnp.asarray(-1, ssort.dtype)))
    ra = a2a(bucketize(asort, jnp.zeros((), asort.dtype)))
    rv = tmap(lambda a: a2a(bucketize(a, jnp.zeros((), a.dtype))), vsort)
    valid = rs >= 0
    shard = lax.axis_index(MESH_AXES)
    local_key = jnp.where(valid, rs - shard * k_local, 0).astype(jnp.int32)
    return rs, ra, rv, valid, local_key, order, flat, ok


def _route_back(ns: int, C: int, routed, order, flat, ok, fill=0):
    """Inverse shuffle: per-received-row results (the owner's outputs, in
    the recv layout ``j*C + c``) return to their source shard — tiled
    all_to_all with equal split/concat axes is an involution — and
    un-permute to the original arrival positions."""
    import jax.numpy as jnp
    from jax import lax

    ret = lax.all_to_all(routed.reshape(ns, C), MESH_AXES, 0, 0,
                         tiled=True).reshape(-1)
    picked = ret[flat]
    out = jnp.full((order.shape[0],), fill, dtype=routed.dtype)
    return out.at[order].set(
        jnp.where(ok, picked, jnp.asarray(fill, routed.dtype)))


def mesh_shard_count(mesh) -> int:
    """Shards of the flat-owner plane: every device of the mesh."""
    return mesh.shape["key"] * mesh.shape["data"]


def make_mesh_table(mesh, state_init, K_pad: int):
    """Per-key state table block-sharded over the flattened mesh: a
    pytree of (K_pad, ...) arrays filled with ``state_init`` leaves (the
    grid-scan table the single-chip ``_KeyedStateScan`` keeps on one
    chip, spread over every device's HBM)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P(MESH_AXES))
    return jax.tree_util.tree_map(
        lambda v: jax.device_put(
            jnp.full((K_pad,) + jnp.asarray(v).shape, v,
                     dtype=jnp.asarray(v).dtype), sh), state_init)


def sharded_grid_scan(mesh, func, filter_mode: bool, key_capacity: int,
                      M: int, local_batch: int):
    """Mesh-sharded keyed grid scan: the device core of the sharded
    stateful Map/Filter. One jitted ``shard_map`` step per batch:

      bucket-by-owner -> all_to_all over the flat ('key','data') order
      (tuple payloads ride ICI; the state table never moves) -> per-key
      arrival ranking (sort by owner-local slot, stable in global
      position) -> (k_local x M) grid scan: ``lax.scan`` walks the
      per-key position axis while ``vmap`` covers the shard's slots ->
      outputs return to their source shard via the inverse all_to_all,
      so the emitted batch keeps arrival order.

    ``M`` is the max per-key tuple count of the batch (host-computed,
    power of two — the program signature, cached per M like the
    single-chip plane caches per (M, KB)). Returns ``(step, meta)``:
    ``step(table, slots, gpos, vals) -> (table2, out, n_tuples)`` where
    ``out`` is the per-row output columns (map) or keep mask (filter) in
    arrival order, and ``meta = (K_pad, k_local, GB)``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..tpu.ops_tpu import _grid_scan_core

    ns = mesh_shard_count(mesh)
    K_pad = math.ceil(key_capacity / ns) * ns
    k_local = K_pad // ns
    C = local_batch
    GB = ns * local_batch
    tmap = jax.tree_util.tree_map
    core = _grid_scan_core(func, filter_mode, M, k_local)

    def local_step(table, slots, gpos, vals):
        rs, rg, rv, valid, lkey, order, flat, ok = _route_flat(
            ns, k_local, C, slots, gpos, vals)
        B2 = rs.shape[0]
        # per-key arrival rank: routed recv layout (source shard asc,
        # source slot asc) IS global arrival order, so a stable sort by
        # owner-local slot preserves each key's relative order
        lk = jnp.where(valid, lkey, k_local)
        sort2 = jnp.argsort(lk, stable=True)
        sl = lk[sort2]
        start_of = jnp.searchsorted(sl, jnp.arange(k_local + 1))
        within_sorted = (jnp.arange(B2)
                         - start_of[jnp.clip(sl, 0, k_local)])
        within = jnp.zeros(B2, jnp.int32).at[sort2].set(
            within_sorted.astype(jnp.int32))
        grid_idx = jnp.where(valid,
                             lkey * M + jnp.minimum(within, M - 1),
                             k_local * M).astype(jnp.int32)
        touched = jnp.arange(k_local, dtype=jnp.int32)
        tmask = jnp.ones(k_local, bool)
        # the mesh plane tracks touched slots host-side (_ckpt_dirty);
        # the device bitmap is dropped and DCE'd out of the program
        out, table2, _dirty2 = core(rv, valid, grid_idx, touched, tmask,
                                    table, jnp.zeros((k_local,), bool))
        if filter_mode:
            keep = _route_back(ns, C, out.astype(jnp.int8), order, flat,
                               ok).astype(bool)
            ret = keep
        else:
            ret = tmap(lambda o: _route_back(ns, C, o, order, flat, ok),
                       out)
        n = lax.psum(jnp.sum(valid), MESH_AXES)
        return table2, ret, n

    stepped = wf_shard_map(
        local_step, mesh=mesh,
        in_specs=(P(MESH_AXES), P(MESH_AXES), P(MESH_AXES), P(MESH_AXES)),
        out_specs=(P(MESH_AXES), P(MESH_AXES), P()),
        # the flat-owner shuffle + route-back keep every array varying
        # over both axes; older jax rep-checking cannot type psum over an
        # axis tuple here, and the forest already runs unchecked
        check_vma=False,
    )
    return jax.jit(stepped), (K_pad, k_local, GB)


def sharded_keyed_reduce(mesh, combine, key_capacity: int,
                         local_batch: int):
    """Mesh-sharded keyed Reduce: per-batch ``reduce_by_key`` with the
    KEYBY shuffle lowered to the flat-owner all_to_all and the combine
    running as a segmented associative scan on each key's owner shard —
    the single-chip ``Reduce_TPU`` semantics (one output per distinct
    key per batch, reference ``reduce_gpu.hpp:239-272``) at mesh scale.
    Fields the combine does not return pass through unchanged.

    Returns ``(step, meta)``: ``step(slots, vals) -> (res, touched,
    n_tuples)`` where ``res`` maps each field to a (K_pad,) array of
    per-slot combine results and ``touched`` is the (K_pad,) bool mask
    of slots this batch touched; ``meta = (K_pad, k_local, GB)``."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    ns = mesh_shard_count(mesh)
    K_pad = math.ceil(key_capacity / ns) * ns
    k_local = K_pad // ns
    C = local_batch
    GB = ns * local_batch
    tmap = jax.tree_util.tree_map

    def local_step(slots, vals):
        rs, _, rv, valid, lkey, _, _, _ = _route_flat(
            ns, k_local, C, slots, slots, vals)
        B2 = rs.shape[0]
        lk = jnp.where(valid, lkey, k_local)
        order = jnp.argsort(lk, stable=True)  # arrival order within key
        sl = lk[order]
        sv = tmap(lambda a: a[order], rv)

        def seg_op(a, b):
            fa, sa = a
            fb, sb = b
            same = sa == sb
            merged = combine(fa, fb)
            out = {k: jnp.where(same, merged.get(k, fb[k]), fb[k])
                   for k in fb}
            return out, sb

        scanned, _ = lax.associative_scan(seg_op, (sv, sl))
        is_end = jnp.concatenate(
            [sl[1:] != sl[:-1], jnp.ones((1,), bool)]) & (sl < k_local)
        safe = jnp.where(is_end, sl, k_local)
        res = {f: jnp.zeros((k_local,), v.dtype).at[safe].set(
                   jnp.where(is_end, v, jnp.zeros((), v.dtype)),
                   mode="drop")
               for f, v in scanned.items()}
        touched = jnp.zeros((k_local,), bool).at[safe].set(
            is_end, mode="drop")
        n = lax.psum(jnp.sum(valid), MESH_AXES)
        return res, touched, n

    stepped = wf_shard_map(
        local_step, mesh=mesh,
        in_specs=(P(MESH_AXES), P(MESH_AXES)),
        out_specs=(P(MESH_AXES), P(MESH_AXES), P()),
        check_vma=False,
    )
    return jax.jit(stepped), (K_pad, k_local, GB)


def mesh_occupancy(n_slots: int, k_local: int, ns: int):
    """(max per-shard slot occupancy, skew) for ``n_slots`` dense
    first-seen slots block-owned ``slot // k_local`` over ``ns`` shards.
    Skew is max/mean — 1.0 when keys fill the shards evenly, ns when a
    single shard owns everything (dense slot assignment fills shard 0
    first, so early-stream skew is expected and decays as keys arrive)."""
    if n_slots <= 0 or ns <= 0 or k_local <= 0:
        return 0, 0.0
    occ_max = k_local if n_slots >= k_local else n_slots
    mean = n_slots / ns
    return occ_max, round(occ_max / mean, 3) if mean > 0 else 0.0
