"""Ffat_Windows_Mesh: the sharded FlatFAT forest as a FRAMEWORK operator.

Round-2 verdict: ``parallel/mesh.py`` was a standalone library — no
builder, operator, or PipeGraph path reached it. This module closes that
gap: a topology-level operator whose single host replica drives
``parallel.sharded_ffat_forest`` over a ``jax.sharding.Mesh``, so a real
pipeline (CPU source -> keyed staging -> sharded forest across chips ->
CPU sink) runs THROUGH the topology layer. Construct it with
``Ffat_Windows_TPU_Builder(...).with_mesh(...)``.

Design (vs the single-chip ``tpu/ffat_tpu.py``):
- the keyby SHUFFLE moves from inter-replica channels to ``lax.all_to_all``
  over the mesh's ICI (the reference's analogous plane is the GPU keyby
  emitter wired into the topology, ``wf/keyby_emitter_gpu.hpp:518-583``;
  here the topology edge stays single-destination — one host replica — and
  the per-key routing happens inside the jitted step);
- per-key control state (next_fire / max_leaf / fired) lives ON DEVICE in
  the shard that owns the key: firing decisions need no host metadata and
  no cross-chip traffic;
- window semantics are ORIGIN-ANCHORED: window ``w`` of a key covers panes
  ``[w*slide, w*slide + win)`` from the epoch, and empty eligible windows
  fire with ``valid=False`` — the reference's TB numbering
  (``wf/window_replica.hpp:253-283``), NOT the single-chip plane's
  first-tuple anchoring (PARITY.md §2.3 documents that divergence);
- keys may be ARBITRARY integers (any int64, sparse or negative): a host
  ``KeySlotMap`` assigns each distinct key a dense slot in
  ``[0, key_capacity)`` in first-seen order — the same dictionary the
  single-chip plane routes through — and the slot feeds the block-owner
  mapping (shard ``s`` owns slots ``[s*k_local, (s+1)*k_local)``); fired
  windows carry the ORIGINAL key. More distinct keys than
  ``key_capacity`` raise loudly (``with_key_capacity`` is the knob).
  Non-integer key types stay single-chip-only: their per-row Python
  hashing would serialize the mesh's host control loop;
- lateness is a per-key rule enforced on device. The DEFAULT
  (``late_policy="keep_open"``) drops a tuple (counted ignored) iff
  every window containing its pane has already fired for its key —
  ``pane < next_fire[key]`` — a deliberate LESS-LOSSY divergence from
  the reference, which drops any tuple inside the last fired window
  even when it still belongs to open windows
  (``wf/window_replica.hpp:257-258``: ``index < win + last_lwid*slide``,
  only once a window fired). ``late_policy="ref_fired"`` reproduces the
  reference bound exactly (``pane < next_fire + win - slide`` once
  ``next_fire > 0``). Either way the only host-side drop is panes
  below the first batch's slide-aligned rebase anchor, which the device
  pane domain cannot represent. Keys that go idle are fast-forwarded past
  the frontier inside the step (their skipped windows are provably
  empty), so an idle-resume key can never read aliased ring leaves; and
  tuples more than ``ring - win`` panes AHEAD of the frontier trigger
  host-driven ring GROWTH with leaf migration (the single-chip plane's
  ``_grow_ring`` analog: geometric doubling, one step recompile per
  growth, internal levels rebuilt by the next firing step) — growth past
  ``RING_CAP_PANES`` (2^20 panes per key) is refused with a loud error,
  since an outrun that large is a watermark bug; ``with_mesh(ring_panes=)``
  pre-sizes the ring for known-bursty sources.

One step per staged input batch (padded to the mesh's global batch with
key = -1 lanes, which the routing drops); partial tail batches therefore
add bounded latency, never unbounded buffering.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..basic import OpType, RoutingMode, WinType, WindFlowError
from ..tpu.batch import BatchTPU
from ..tpu.ops_tpu import TPUOperatorBase, TPUReplicaBase
from ..tpu.schema import TupleSchema


class Ffat_Windows_Mesh(TPUOperatorBase):
    """Keyed sliding-window aggregation sharded over a device mesh."""

    op_type = OpType.WIN_TPU
    # mesh execution plane: parallelism is the mesh shape, not the
    # replica count (rescale/autoscale refuse via repartition_refusal);
    # snapshot/restore ships per-shard state blocks under one manifest
    # entry and can relayout onto a different mesh factorization
    is_mesh = True
    mesh_snapshot_capable = True

    def __init__(self, lift: Callable, combine: Callable, key_extractor,
                 win_len: int, slide_len: int,
                 win_type: WinType = WinType.TB, lateness: int = 0,
                 name: str = "ffat_windows_mesh",
                 key_capacity: int = 16,
                 n_devices: Optional[int] = None,
                 mesh_shape: Optional[tuple] = None,
                 local_batch: Optional[int] = None,
                 fire_rounds: int = 4,
                 ring_panes: int = 0,
                 late_policy: str = "keep_open",
                 schema: Optional[TupleSchema] = None) -> None:
        if key_extractor is None:
            raise WindFlowError(f"{name}: requires a key extractor")
        if win_type is not WinType.TB:
            raise WindFlowError(
                f"{name}: the mesh plane supports TB windows (CB arrival "
                "indexing needs per-key host counters; use the single-chip "
                "Ffat_Windows_TPU)")
        if win_len <= 0 or slide_len <= 0:
            raise WindFlowError(f"{name}: win/slide must be > 0")
        # ONE host replica drives the whole mesh; parallelism is the mesh
        super().__init__(name, 1, RoutingMode.KEYBY, key_extractor, 0,
                         schema)
        self.lift = lift
        self.combine = combine
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.lateness = lateness
        self.key_capacity = max(1, key_capacity)
        self.n_devices = n_devices
        self.mesh_shape = mesh_shape
        self.local_batch = local_batch
        if late_policy not in ("keep_open", "ref_fired"):
            raise WindFlowError(
                f"{name}: late_policy must be 'keep_open' or 'ref_fired' "
                f"(got {late_policy!r})")
        self.fire_rounds = max(1, fire_rounds)
        self.ring_panes = ring_panes
        self.late_policy = late_policy
        self.pane_len = math.gcd(win_len, slide_len)

    def build_replicas(self) -> None:
        self.replicas = [FfatMeshReplica(self, 0)]


class FfatMeshReplica(TPUReplicaBase):
    """Host control loop: staged batch -> sharded step -> fired windows."""

    def __init__(self, op: Ffat_Windows_Mesh, idx: int) -> None:
        super().__init__(op, idx)
        self.win_units = op.win_len // op.pane_len
        self.slide_units = op.slide_len // op.pane_len
        self._mesh = None  # lazy: the device mesh exists at run time only
        self._step = None
        self._state = None
        self._sharding = None
        self._GB = 0
        self._K_pad = 0
        self._F = 0
        self._val_fields: List[str] = []
        self._val_dtypes: Dict[str, Any] = {}
        self._out_fields: List[str] = []
        self._frontier = 0        # REBASED panes (see _pane_base)
        self._max_pane_seen = -1  # rebased
        # pane REBASE: epoch-µs timestamps make ts//pane_len overflow the
        # device's int32 pane domain immediately; the first batch anchors
        # a base (rounded DOWN to a slide multiple so window numbering
        # stays origin-anchored), device panes are pane-base, and emitted
        # wids add base//slide back (host int64)
        self._pane_base: Optional[int] = None
        # host upper bound on the per-key fired-window backlog (frontier
        # advanced minus fire_rounds per step): eviction lags firing, so
        # ring-aliasing safety must account for it (see _maybe_catch_up)
        self._backlog_bound = 0
        # restored snapshot awaiting relayout (applied in _ensure once
        # the mesh exists; snapshot_state passes it through untouched)
        self._pending_restore: Optional[dict] = None
        # arbitrary int keys -> dense slots [0, key_capacity) in
        # first-seen order; fired windows map slots back to originals
        from ..tpu.keymap import KeySlotMap
        self._key_by_slot = np.zeros(op.key_capacity, np.int64)
        self._keymap = KeySlotMap(on_new=self._on_new_key)

    def _on_new_key(self, key, slot: int) -> None:
        if slot >= self.op.key_capacity:
            from ..basic import KeyCapacityError
            raise KeyCapacityError(
                self.op.name,
                getattr(self, "_K_pad", 0) or self.op.key_capacity,
                slot - self.op.key_capacity + 1,
                hint="raise with_key_capacity")
        self._key_by_slot[slot] = key

    # -- lazy mesh/program construction ---------------------------------
    def _ensure(self, batch: Optional[BatchTPU]) -> None:
        """Build mesh + sharded step. ``batch=None`` builds from a
        pending restored snapshot's metadata (a watermark-only advance or
        EOS flush can need the restored forest before any batch arrives);
        a restored snapshot's state is relayouted in either case."""
        if self._step is not None:
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .core import make_key_mesh

        pend = getattr(self, "_pending_restore", None)
        if batch is None and pend is None:
            return
        op = self.op
        n_dev = op.n_devices or len(jax.devices())
        self._mesh = make_key_mesh(n_dev, shape=op.mesh_shape)
        ka = self._mesh.shape["key"]
        da = self._mesh.shape["data"]
        if batch is not None:
            local_batch = op.local_batch or max(
                1, math.ceil(batch.capacity / (ka * da)))
            self._val_fields = list(batch.fields.keys())
            self._val_dtypes = {f: batch.schema.fields[f]
                                for f in self._val_fields}
        else:
            local_batch = op.local_batch or pend["local_batch"]
            self._val_dtypes = {f: np.dtype(dt)
                                for f, dt in pend["val_dtypes"].items()}
            self._val_fields = list(self._val_dtypes.keys())
        from .core import default_ring_panes
        self._F = op.ring_panes or default_ring_panes(
            self.win_units, self.slide_units, op.fire_rounds)
        if pend is not None:
            # ring geometry is state: the restored forest's leaf layout
            # is pane % F, so the rebuilt step must use the SAME F
            self._F = max(self._F, int(pend["F"]))
            if self._F != int(pend["F"]):
                # a larger configured ring: migrate like ring growth does
                pass  # relayout below re-maps leaves pane-wise
        self._local_batch = local_batch
        init_fn, step, (K_pad, k_local, GB) = self._build_forest(self._F)
        self._step = step
        self._GB, self._K_pad = GB, K_pad
        sample = {f: np.zeros(1, dt) for f, dt in self._val_dtypes.items()}
        self._out_fields = list(jax.eval_shape(
            lambda v: op.lift(v), sample).keys())
        self._state = init_fn(sample)
        self._sharding = NamedSharding(self._mesh, P(("key", "data")))
        self.stats.mesh_devices = ka * da
        from .core import excluded_device_ids
        if excluded_device_ids():
            want = min(n_dev, len(jax.devices()))
            self.stats.mesh_degraded = max(0, want - ka * da)
        else:
            self.stats.mesh_degraded = 0
        if pend is not None:
            self._apply_pending_restore()

    def _build_forest(self, ring_panes: int):
        """ONE construction path for the sharded step (initial build and
        ring growth must never drift apart in config or error handling)."""
        from .core import sharded_ffat_forest

        op = self.op
        try:
            return sharded_ffat_forest(
                self._mesh, op.lift, op.combine, n_keys=op.key_capacity,
                win_panes=self.win_units, slide_panes=self.slide_units,
                local_batch=self._local_batch,
                fire_rounds=op.fire_rounds, ring_panes=ring_panes,
                late_policy=op.late_policy)
        except ValueError as e:  # config validation -> framework error
            raise WindFlowError(f"{op.name}: {e}") from None

    # -- sharded fault tolerance ----------------------------------------
    def snapshot_state(self) -> dict:
        """Aligned snapshot: host control state + the forest as PER-SHARD
        row blocks gathered under one manifest entry (one blob per mesh
        operator; each block is one key-shard's rows, so restore can
        relayout onto a different mesh factorization or device count by
        slot-row gather)."""
        import time as _time

        st = super().snapshot_state()  # drains the dispatch queue
        pend = getattr(self, "_pending_restore", None)
        if self._step is None:
            if pend is not None:
                # restored but never touched since: the restored blob is
                # still the exact state — pass it through unchanged
                st["mesh_ffat"] = pend
            return st
        import jax

        t0 = _time.perf_counter()
        ns = self._mesh.shape["key"]
        trees, tvalid, nf, ml, fired = self._state
        blocks = lambda a: np.split(np.ascontiguousarray(
            np.asarray(jax.device_get(a))), ns, axis=0)
        st["mesh_ffat"] = {
            "slot_of_key": dict(self._keymap.slot_of_key),
            "key_by_slot": self._key_by_slot.copy(),
            "key_capacity": self.op.key_capacity,
            "val_dtypes": {f: np.dtype(dt).str
                           for f, dt in self._val_dtypes.items()},
            "local_batch": self._local_batch,
            "F": self._F, "K_pad": self._K_pad, "key_shards": ns,
            "pane_base": self._pane_base,
            "frontier": self._frontier,
            "max_pane_seen": self._max_pane_seen,
            "backlog_bound": self._backlog_bound,
            "trees": {f: blocks(a) for f, a in trees.items()},
            "tvalid": blocks(tvalid),
            "next_fire": blocks(nf),
            "max_leaf": blocks(ml),
            "fired": blocks(fired),
        }
        rec = self.stats.recorder
        if rec is not None:
            rec.event("mesh:snapshot",
                      (_time.perf_counter() - t0) * 1e6,
                      {"keys": len(self._keymap.slot_of_key),
                       "shards": ns})
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        d = state.get("mesh_ffat")
        if d is not None:
            # applied lazily once the mesh exists (_ensure): restore runs
            # before workers start, and the target mesh factorization may
            # differ from the checkpointed one
            self._pending_restore = d

    def _apply_pending_restore(self) -> None:
        """Relayout the restored forest onto THIS mesh: per-shard blocks
        concatenate to the global slot axis, rows re-pad to the new
        K_pad, and live leaves re-map ``pane % F_old -> pane % F_new``
        (identity for an unchanged ring; the ring-growth migration
        otherwise). Runs after ``_build_forest`` so initial build and
        restore share one construction path."""
        import time as _time

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        d, self._pending_restore = self._pending_restore, None
        op = self.op
        t0 = _time.perf_counter()
        if len(d["slot_of_key"]) > op.key_capacity:
            raise WindFlowError(
                f"{op.name}: restore holds {len(d['slot_of_key'])} "
                f"distinct keys but this graph declares key_capacity="
                f"{op.key_capacity}; raise with_key_capacity to at least "
                "the checkpointed count")
        if set(d["trees"]) != set(self._state[0]):
            raise WindFlowError(
                f"{op.name}: restored forest fields "
                f"{sorted(d['trees'])} do not match this graph's lift "
                f"output {sorted(self._state[0])} — the checkpointed "
                "operator ran a different aggregation")
        self._keymap.slot_of_key.clear()
        self._keymap.slot_of_key.update(d["slot_of_key"])
        self._keymap._lut = None
        kbs = np.asarray(d["key_by_slot"])
        self._key_by_slot[:] = 0
        n_copy = min(len(kbs), op.key_capacity)
        self._key_by_slot[:n_copy] = kbs[:n_copy]
        self._pane_base = d["pane_base"]
        self._frontier = int(d["frontier"])
        self._max_pane_seen = int(d["max_pane_seen"])
        self._backlog_bound = int(d["backlog_bound"])

        full = lambda bl: np.concatenate([np.asarray(b) for b in bl],
                                         axis=0)
        K_new, F_new, F_old = self._K_pad, self._F, int(d["F"])
        nf_old = full(d["next_fire"]).astype(np.int64)
        ml_old = full(d["max_leaf"]).astype(np.int64)
        fired_old = full(d["fired"])
        tvalid_old = full(d["tvalid"])
        trees_old = {f: full(bl) for f, bl in d["trees"].items()}
        K_old = tvalid_old.shape[0]
        # live slots all sit below key_capacity <= min(K_old, K_new):
        # rows beyond are untouched padding on either side
        rows_k = min(K_old, K_new)

        def fit_rows(a, fill):
            out = np.full((K_new,) + a.shape[1:], fill, dtype=a.dtype)
            out[:rows_k] = a[:rows_k]
            return out

        nf = fit_rows(nf_old, 0)
        ml = fit_rows(ml_old, -1)
        fired = fit_rows(fired_old, 0)
        spans = np.maximum(0, ml - nf + 1)
        spans[rows_k:] = 0
        rows = np.repeat(np.arange(K_new), spans)
        before = np.cumsum(spans) - spans
        seg = np.arange(int(spans.sum()), dtype=np.int64) \
            - np.repeat(before, spans)
        panes = np.repeat(nf, spans) + seg
        src = (F_old + (panes % F_old)).astype(np.int64)
        dst = (F_new + (panes % F_new)).astype(np.int64)
        new_trees = {f: np.zeros((K_new, 2 * F_new), t.dtype)
                     for f, t in trees_old.items()}
        new_tvalid = np.zeros((K_new, 2 * F_new), bool)
        for f, t in trees_old.items():
            new_trees[f][rows, dst] = t[rows, src]
        new_tvalid[rows, dst] = tvalid_old[rows, src]
        # internal levels stay invalid — the first firing step's
        # in-program rebuild recomputes them from leaves (the same
        # contract ring growth relies on)
        sh_keys = NamedSharding(self._mesh, P("key", None))
        sh_key1 = NamedSharding(self._mesh, P("key"))
        self._state = (
            {f: jax.device_put(a, sh_keys)
             for f, a in new_trees.items()},
            jax.device_put(new_tvalid, sh_keys),
            jax.device_put(nf.astype(np.int32), sh_key1),
            jax.device_put(ml.astype(np.int32), sh_key1),
            jax.device_put(fired.astype(np.int32), sh_key1))
        rec = self.stats.recorder
        if rec is not None:
            rec.event("mesh:restore",
                      (_time.perf_counter() - t0) * 1e6,
                      {"keys": len(self._keymap.slot_of_key),
                       "F": F_new, "K_pad": K_new})

    # -- streaming ------------------------------------------------------
    def _rebased_frontier(self, wm: Optional[int] = None) -> int:
        """Frontier from ``wm`` (default: the replica watermark). Batch
        commits MUST pass their batch's own arrival-time watermark: the
        dispatch pipeline defers commits, so by commit time ``cur_wm``
        may already reflect LATER batches — folding this batch under
        that future frontier would fast-forward keys past panes still
        in this very batch and drop them as late."""
        if wm is None:
            wm = self.cur_wm
        f_abs = max(0, wm - self.op.lateness) // self.op.pane_len
        return max(0, f_abs - (self._pane_base or 0))

    def _advance_frontier(self, new_frontier: int) -> bool:
        """Move the fire frontier and accrue the fired-window backlog it
        creates (up to ceil(delta/slide) new fireable windows per key) —
        accrual must happen HERE, before any ring-headroom check reads
        the bound."""
        if new_frontier <= self._frontier:
            return False
        delta = new_frontier - self._frontier
        self._frontier = new_frontier
        self._backlog_bound += -(-delta // self.slide_units)
        return True

    def process_device_batch(self, batch: BatchTPU) -> None:
        self._ensure(batch)
        n = batch.size
        keys = np.asarray(self.batch_keys(batch))[:n]
        if keys.dtype.kind not in "iu":
            raise WindFlowError(
                f"{self.op.name}: mesh FFAT requires integer keys "
                f"(sparse/negative int64 ok); got dtype {keys.dtype}")
        # arbitrary int domain -> dense slots (the capacity guard lives
        # in _on_new_key: it fires against the DECLARED capacity, not
        # the mesh-padded K_pad — acceptance must not depend on shape;
        # slots stay in the keymap's narrow dtype, _run_steps casts once)
        keys = self._keymap.slots_of(keys, keys, n)
        from .core import mesh_occupancy
        occ, skew = mesh_occupancy(
            len(self._keymap), self._K_pad // self._mesh.shape["key"],
            self._mesh.shape["key"])
        self.stats.mesh_shard_occupancy = occ
        self.stats.mesh_shard_skew = skew
        panes = (batch.ts_host[:n] // self.op.pane_len).astype(np.int64)
        if self._pane_base is None:
            base = int(panes.min()) if n else 0
            self._pane_base = (base // self.slide_units) * self.slide_units
        panes = panes - self._pane_base
        # frontier: the single-chip convention ((wm - lateness) // pane),
        # from THIS batch's arrival-time watermark — commits are
        # deferred, so the replica watermark may already be ahead
        self._advance_frontier(self._rebased_frontier(batch.wm))
        # the per-key lateness rule (late_policy: "keep_open" drops iff
        # every containing window fired; "ref_fired" also drops inside
        # the last fired window) lives ON DEVICE as a mask on next_fire;
        # the host only drops panes below the rebase anchor (the first
        # batch's slide-aligned min pane — the device pane domain cannot
        # represent them; counted ignored, a documented anchor divergence)
        live = panes >= 0
        dropped = n - int(live.sum())
        # unified late accounting, arrival side: anchor drops are counted
        # records+dropped here; rows behind this batch's watermark are
        # counted records-only — the per-key drop decision is deferred to
        # the device program, whose count rides the existing fire
        # readback in _run_steps (drop-only there, no double count and
        # NO new host sync)
        st = self.stats
        ts_live = batch.ts_host[:n][live] if dropped else batch.ts_host[:n]
        panes_live = panes[live] if dropped else panes
        # behind this batch's watermark, OR behind the replica's fire
        # frontier (a slower input channel's wm can trail it; the device
        # drop rule compares against per-key next_fire ≤ frontier, so
        # this mask is a strict superset of every deferred device drop)
        late_mask = (ts_live < batch.wm) | (panes_live < self._frontier)
        n_late_seen = int(late_mask.sum())
        if n_late_seen or dropped:
            st.note_late(n_late_seen + dropped, dropped,
                         batch.wm - ts_live[late_mask]
                         if st.hist_lateness is not None and n_late_seen
                         else None)
        if dropped:
            self.stats.inputs_ignored += dropped
            keys, panes = keys[live], panes[live]
        if panes.size:
            self._check_ring_headroom(int(panes.max()))
            if int(panes.max()) >= np.iinfo(np.int32).max:
                raise WindFlowError(
                    f"{self.op.name}: rebased pane {int(panes.max())} "
                    "overflows the device's int32 pane domain; use a "
                    "larger pane (win/slide gcd)")
            self._max_pane_seen = max(self._max_pane_seen, int(panes.max()))
        vals = {f: np.asarray(batch.fields[f])[:n][live]
                for f in self._val_fields}
        self._run_steps(keys.astype(np.int32), panes.astype(np.int32), vals)

    def on_punctuation(self, wm: int) -> None:
        # a watermark-only advance can make windows fireable with no new
        # data: run a data-less step when the frontier moved (only once
        # data anchored the pane rebase — before that the absolute
        # epoch-µs frontier would poison the rebased domain)
        if self._step is None and self._pending_restore is not None:
            self._ensure(None)  # restored forest, no batch yet
        if self._step is not None and self._pane_base is not None:
            if self._advance_frontier(self._rebased_frontier()):
                self._run_steps(np.zeros(0, np.int32),
                                np.zeros(0, np.int32), self._empty_vals())
        super().on_punctuation(wm)

    # -- ring-aliasing safety -------------------------------------------
    def _check_ring_headroom(self, max_pane: int) -> None:
        """A new pane ``p`` of key k aliases k's circular leaf ring iff
        ``p >= next_fire[k] + F`` (leaves below next_fire are evicted;
        key rows are independent). next_fire trails the frontier by the
        per-key fired-window BACKLOG (each step fires at most fire_rounds
        windows), tracked conservatively on the host; when the slack is
        gone, data-less catch-up steps fire + evict until the device
        control state shows the backlog cleared."""
        while True:
            floor = (self._frontier - self.win_units + 1
                     - self._backlog_bound * self.slide_units)
            if max_pane < floor + self._F and max_pane < self._frontier \
                    + self._F - self.win_units:
                return
            if self._backlog_bound > 0:
                self._catch_up()
                continue
            if self._grow_ring_to(max_pane):
                continue  # re-check against the grown ring
            raise WindFlowError(
                f"{self.op.name}: pane {max_pane} is more than ring-win "
                f"({self._F}-{self.win_units}) panes ahead of the "
                f"watermark frontier {self._frontier}, and growing the "
                f"ring past {self.RING_CAP_PANES} panes is refused "
                "(a source outrunning its watermarks by that much is a "
                "watermark bug); advance watermarks faster or raise "
                "with_mesh(ring_panes=...)")

    RING_CAP_PANES = 1 << 20  # growth refusal threshold (per-key panes)

    def _grow_ring_to(self, max_pane: int) -> bool:
        """Ring growth with state migration — the mesh analog of the
        single-chip plane's ``_grow_ring`` (a source briefly outrunning
        its watermarks must not be fatal). Host-driven: fetch the forest,
        re-map LIVE LEAVES ``pane % F -> pane % F'`` per key, rebuild the
        sharded step for the larger ring, and re-shard the migrated
        state. Internal levels are left invalid — the first firing
        step's in-program rebuild recomputes them from leaves (the same
        contract the conditional rebuild relies on). Returns False when
        the needed ring exceeds RING_CAP_PANES (caller raises)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        op = self.op
        new_F = self._F
        while (max_pane - self._frontier + self.win_units >= new_F
               or new_F < self.win_units
               + op.fire_rounds * self.slide_units):
            new_F *= 2
            if new_F > self.RING_CAP_PANES:
                return False
        trees = {f: np.asarray(v) for f, v in self._state[0].items()}
        tvalid = np.asarray(self._state[1])
        nf = np.asarray(self._state[2]).astype(np.int64)
        ml = np.asarray(self._state[3]).astype(np.int64)
        fired = np.asarray(self._state[4])
        K_pad = tvalid.shape[0]
        old_F = self._F
        spans = np.maximum(0, ml - nf + 1)
        rows = np.repeat(np.arange(K_pad), spans)
        before = np.cumsum(spans) - spans
        seg = np.arange(int(spans.sum()), dtype=np.int64) \
            - np.repeat(before, spans)
        panes = np.repeat(nf, spans) + seg
        src = old_F + (panes % old_F)
        dst = new_F + (panes % new_F)
        new_trees = {f: np.zeros((K_pad, 2 * new_F), t.dtype)
                     for f, t in trees.items()}
        new_tvalid = np.zeros((K_pad, 2 * new_F), bool)
        for f, t in trees.items():
            new_trees[f][rows, dst] = t[rows, src]
        new_tvalid[rows, dst] = tvalid[rows, src]
        _init, step, (_kp, _kl, _gb) = self._build_forest(new_F)
        sh_keys = NamedSharding(self._mesh, P("key", None))
        sh_key1 = NamedSharding(self._mesh, P("key"))
        self._step = step
        self._state = (
            {f: jax.device_put(a, sh_keys) for f, a in new_trees.items()},
            jax.device_put(new_tvalid, sh_keys),
            jax.device_put(nf.astype(np.int32), sh_key1),
            jax.device_put(ml.astype(np.int32), sh_key1),
            jax.device_put(fired, sh_key1))
        self._F = new_F
        return True

    def _catch_up(self) -> None:
        """Fire the backlog with data-less steps. ONE control-state fetch
        sizes the whole drain (per-iteration D2H costs ~70 ms fixed on the
        tunnel): each key can fire ``min((frontier-win-nf)//slide,
        (ml-nf)//slide) + 1`` windows — the device's own eligibility rule
        — and every step fires up to fire_rounds of them per key."""
        nf = np.asarray(self._state[2]).astype(np.int64)
        ml = np.asarray(self._state[3]).astype(np.int64)
        per_key = np.minimum(
            (self._frontier - self.win_units - nf) // self.slide_units,
            (ml - nf) // self.slide_units) + 1
        n_win = int(np.maximum(per_key, 0).max(initial=0))
        for _ in range(-(-n_win // self.op.fire_rounds)):
            self._run_steps(np.zeros(0, np.int32), np.zeros(0, np.int32),
                            self._empty_vals())
        self._backlog_bound = 0

    def _empty_vals(self) -> Dict[str, np.ndarray]:
        return {f: np.zeros(0, dt) for f, dt in self._val_dtypes.items()}

    def _run_steps(self, keys, panes, vals) -> None:
        """Feed ``GB``-sized slices (padded with key=-1 lanes) through the
        sharded step; emit fired windows after each."""
        import time as _time

        import jax

        GB = self._GB
        total = keys.shape[0]
        off = 0
        # per-step shuffle traffic: every tuple column rides the
        # all_to_all once (keys + panes int32 + the value columns)
        step_bytes = GB * (8 + sum(np.dtype(dt).itemsize
                                   for dt in self._val_dtypes.values()))
        while True:
            t0 = _time.perf_counter()
            lo, hi = off, min(off + GB, total)
            m = hi - lo
            k_sl = np.full(GB, -1, np.int32)
            p_sl = np.zeros(GB, np.int32)
            k_sl[:m] = keys[lo:hi]
            p_sl[:m] = panes[lo:hi]
            v_sl = {}
            for f, col in vals.items():
                buf = np.zeros((GB,) + col.shape[1:], col.dtype)
                buf[:m] = col[lo:hi]
                v_sl[f] = jax.device_put(buf, self._sharding)
            out = self._step(
                *self._state, jax.device_put(k_sl, self._sharding),
                v_sl, jax.device_put(p_sl, self._sharding),
                np.int32(min(self._frontier, np.iinfo(np.int32).max)))
            self._state = out[:5]
            self.stats.device_programs_run += 1
            self.stats.note_mesh_step(
                (_time.perf_counter() - t0) * 1e6, step_bytes)
            self._backlog_bound = max(0,
                                      self._backlog_bound
                                      - self.op.fire_rounds)
            n_late = int(out[9])
            if n_late:
                self.stats.inputs_ignored += n_late
                # in-program late count riding the existing readback:
                # drop-only — these rows were already counted into
                # late_records at arrival (every device-dropped pane sits
                # behind the watermark frontier of its batch)
                self.stats.note_late(0, n_late)
            self._emit_fired(out[5], out[6], out[7])
            off = hi
            if off >= total:
                break

    def _emit_fired(self, res, res_valid, res_wid) -> None:
        """Harvest the step's fired-window block (K_pad x fire_rounds —
        small) and emit ONE columnar batch per step through the exit
        edge, like the single-chip plane (``tpu/ffat_tpu.py`` emits one
        ``BatchTPU`` per fire sweep): numpy gathers only, no per-window
        Python loop. Rows carry ``valid`` — the aggregate fields of a
        ``valid=False`` (empty-window) row are meaningless, matching the
        single-chip plane's columnar contract."""
        rw = np.asarray(res_wid)
        fired = rw >= 0
        n_out = int(fired.sum())
        if not n_out:
            return
        rv = np.asarray(res_valid)
        key_field = self.op.key_field or "key"
        wid_base = (self._pane_base or 0) // self.slide_units
        krows, rounds = np.nonzero(fired)
        wids = rw[krows, rounds].astype(np.int64) + wid_base
        end_ts = (wids * self.slide_units + self.win_units) \
            * self.op.pane_len
        fields: Dict[str, np.ndarray] = {
            key_field: self._key_by_slot[krows],  # slots -> original keys
            "wid": wids,
            "valid": rv[krows, rounds],
        }
        for f in self._out_fields:
            fields[f] = np.asarray(res[f])[krows, rounds]
        schema = TupleSchema({name: np.dtype(col.dtype)
                              for name, col in fields.items()})
        out = BatchTPU(fields, end_ts, n_out, schema, self.cur_wm,
                       host_keys=fields[key_field])
        self._emit_batch(out)

    def flush_on_termination(self) -> None:
        """EOS: fire every remaining window that holds data (partial
        windows fire with their partial content, like the single-chip
        plane's EOS flush)."""
        if self._step is None and self._pending_restore is not None:
            self._ensure(None)  # restored forest, no batch since
        if self._step is None or self._max_pane_seen < 0:
            return
        self._advance_frontier(self._max_pane_seen + self.win_units + 1)
        # ONE control-state fetch sizes the drain (no per-iteration D2H):
        # with the frontier past every pane, key k has (ml-nf)//slide + 1
        # windows left; each data-less step fires up to fire_rounds of
        # them per key
        nf = np.asarray(self._state[2]).astype(np.int64)  # next_fire
        ml = np.asarray(self._state[3]).astype(np.int64)  # max_leaf
        per_key = (ml - nf) // self.slide_units + 1
        n_win = int(np.maximum(per_key, 0).max(initial=0))
        for _ in range(-(-n_win // self.op.fire_rounds)):
            self._run_steps(np.zeros(0, np.int32), np.zeros(0, np.int32),
                            self._empty_vals())
