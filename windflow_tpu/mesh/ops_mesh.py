"""Mesh-sharded keyed operators: Map_Mesh / Filter_Mesh / Reduce_Mesh.

The keyed-state plane of the single-chip device operators, sharded over
a device mesh (ROADMAP: "key cardinality and state size scale with
devices instead of one chip's HBM"):

- **stateful Map/Filter** (``Map_TPU_Builder(...).with_state(...)
  .with_mesh(...)``): the per-key grid-scan state table — one row per
  dense key slot — is block-sharded along the slot axis over EVERY
  device of the ``('key','data')`` mesh (flattened owner order,
  ``core.MESH_AXES``; a grid-scan transition is sequential per key, so
  unlike the FFAT forest no associative data-axis merge exists and each
  key lives on exactly one device). One ``shard_map``-jitted step per
  staged batch: bucket-by-owner + ``lax.all_to_all`` (the KEYBY shuffle
  as a device collective — the topology edge into the operator stays
  single-destination, replacing the host-side keyby emitters on this
  edge), the (k_local x M) grid scan on each owner, and an inverse
  all_to_all returning outputs to arrival order;
- **keyed Reduce** (``Reduce_TPU_Builder(...).with_key_by(...)
  .with_mesh(...)``): per-batch ``reduce_by_key`` — the single-chip
  ``Reduce_TPU`` semantics, one output per distinct key per batch —
  with the shuffle and the segmented combine both on device.

Shared mechanics (the ``Ffat_Windows_Mesh`` idiom): ONE host replica
drives the whole mesh; arbitrary int64 keys densify to slots through a
host ``KeySlotMap`` (``key_capacity`` is the declared bound, exceeded =
loud error); batches pad to the mesh's global batch with slot = -1
lanes the routing drops. Fault tolerance: ``snapshot_state`` ships the
state table as PER-SHARD row blocks gathered under one manifest entry;
``restore_state`` relayouts onto a different mesh factorization or
device count by slot-row gather (arXiv:2112.01075's redistribution
decomposition; the ``StateRepartitioner`` idiom at mesh grain).
``rescale()`` refuses mesh operators — parallelism is the mesh shape —
via ``scaling.repartition.repartition_refusal``.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..basic import KeyCapacityError, OpType, RoutingMode, WindFlowError
from ..tpu.batch import BatchTPU, bucket_capacity
from ..tpu.ops_tpu import TPUOperatorBase, TPUReplicaBase, cached_compile
from ..tpu.schema import TupleSchema


# ---------------------------------------------------------------------------
# operators
# ---------------------------------------------------------------------------
class _MeshKeyedOperator(TPUOperatorBase):
    """Shared metadata of the mesh-sharded keyed operators."""

    op_type = OpType.TPU
    # mesh execution plane: parallelism is the mesh shape, not the
    # replica count; snapshot/restore ships per-shard blocks and can
    # relayout onto a different mesh factorization
    is_mesh = True
    mesh_snapshot_capable = True

    def __init__(self, name: str, key_extractor, schema,
                 key_capacity: int, n_devices: Optional[int],
                 mesh_shape: Optional[tuple],
                 local_batch: Optional[int]) -> None:
        if key_extractor is None:
            raise WindFlowError(f"{name}: mesh operators require a key "
                                "extractor (with_key_by)")
        # ONE host replica drives the whole mesh; parallelism is the mesh
        super().__init__(name, 1, RoutingMode.KEYBY, key_extractor, 0,
                         schema)
        self.key_capacity = max(1, int(key_capacity))
        self.n_devices = n_devices
        self.mesh_shape = mesh_shape
        self.local_batch = local_batch


class Map_Mesh(_MeshKeyedOperator):
    """Stateful keyed map over the mesh: ``func(row, state) ->
    (row, state)`` scanned in arrival order, state block-sharded over
    the devices."""

    def __init__(self, func: Callable, state_init: Any, key_extractor,
                 name: str = "map_mesh", key_capacity: int = 1024,
                 n_devices: Optional[int] = None,
                 mesh_shape: Optional[tuple] = None,
                 local_batch: Optional[int] = None,
                 schema: Optional[TupleSchema] = None,
                 tiering=None) -> None:
        if state_init is None:
            raise WindFlowError(
                f"{name}: with_mesh applies to the KEYED-STATE plane; a "
                "stateless Map_TPU is data-parallel already (every chip "
                "can run it) — add with_state(...) or drop with_mesh")
        super().__init__(name, key_extractor, schema, key_capacity,
                         n_devices, mesh_shape, local_batch)
        self.func = func
        self.state_init = state_init
        self.tiering = tiering

    def build_replicas(self) -> None:
        self.replicas = [MapMeshReplica(self, 0)]


class Filter_Mesh(_MeshKeyedOperator):
    """Stateful keyed filter over the mesh: ``pred(row, state) ->
    (keep, state)``; the batch compacts on the host side of the step."""

    def __init__(self, pred: Callable, state_init: Any, key_extractor,
                 name: str = "filter_mesh", key_capacity: int = 1024,
                 n_devices: Optional[int] = None,
                 mesh_shape: Optional[tuple] = None,
                 local_batch: Optional[int] = None,
                 schema: Optional[TupleSchema] = None,
                 tiering=None) -> None:
        if state_init is None:
            raise WindFlowError(
                f"{name}: with_mesh applies to the KEYED-STATE plane; a "
                "stateless Filter_TPU is data-parallel already — add "
                "with_state(...) or drop with_mesh")
        super().__init__(name, key_extractor, schema, key_capacity,
                         n_devices, mesh_shape, local_batch)
        self.pred = pred
        self.state_init = state_init
        self.tiering = tiering

    def build_replicas(self) -> None:
        self.replicas = [FilterMeshReplica(self, 0)]


class Reduce_Mesh(_MeshKeyedOperator):
    """Keyed per-batch reduce over the mesh (``Reduce_TPU`` semantics:
    one output per distinct key per batch; combine associative +
    commutative, ``API:78-80``)."""

    def __init__(self, combine: Callable, key_extractor,
                 name: str = "reduce_mesh", key_capacity: int = 1024,
                 n_devices: Optional[int] = None,
                 mesh_shape: Optional[tuple] = None,
                 local_batch: Optional[int] = None,
                 schema: Optional[TupleSchema] = None) -> None:
        if key_extractor is None:
            raise WindFlowError(
                f"{name}: the GLOBAL (unkeyed) reduce folds one "
                "stream-wide value — there is no keyed plane to shard; "
                "with_mesh requires with_key_by")
        super().__init__(name, key_extractor, schema, key_capacity,
                         n_devices, mesh_shape, local_batch)
        self.combine = combine

    def build_replicas(self) -> None:
        self.replicas = [ReduceMeshReplica(self, 0)]


# ---------------------------------------------------------------------------
# host replicas
# ---------------------------------------------------------------------------
class _MeshReplicaBase(TPUReplicaBase):
    """Shared host control loop: lazy mesh construction, key->slot
    densification, GB-slice padding, mesh stats, and the snapshot/
    restore scaffolding (per-shard blocks, relayout on restore)."""

    def __init__(self, op: _MeshKeyedOperator, idx: int) -> None:
        super().__init__(op, idx)
        from ..tpu.keymap import KeySlotMap
        self._key_by_slot = np.zeros(op.key_capacity, np.int64)
        self._keymap = KeySlotMap(on_new=self._on_new_key)
        self._mesh = None  # lazy: the device mesh exists at run time only
        self._sharding = None
        self._ns = 0
        self._k_local = 0
        self._K_pad = 0
        self._GB = 0
        self._local_batch = 0
        self._val_fields: List[str] = []
        self._val_dtypes: Dict[str, np.dtype] = {}
        self._gpos_dev = None
        self._step_bytes = 0
        self._pending_restore: Optional[dict] = None
        self._tier = None  # _MeshScanReplicaBase builds it when declared

    def _on_new_key(self, key, slot: int) -> None:
        if slot >= self.op.key_capacity:
            raise KeyCapacityError(
                self.op.name, self._K_pad or self.op.key_capacity,
                slot - self.op.key_capacity + 1,
                hint="raise with_mesh(key_capacity=) or enable "
                     "with_tiering to spill the cold key tail")
        self._key_by_slot[slot] = key

    # -- lazy mesh/program construction ---------------------------------
    def _mesh_ensure(self, val_dtypes: Dict[str, Any], cap: int) -> None:
        if self._mesh is not None:
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .core import MESH_AXES, make_key_mesh, mesh_shard_count

        op = self.op
        n_dev = op.n_devices or len(jax.devices())
        self._mesh = make_key_mesh(n_dev, shape=op.mesh_shape)
        ns = mesh_shard_count(self._mesh)
        self._ns = ns
        self._note_degraded(n_dev, ns)
        self._local_batch = op.local_batch or max(1, math.ceil(cap / ns))
        self._GB = ns * self._local_batch
        self._K_pad = math.ceil(op.key_capacity / ns) * ns
        self._k_local = self._K_pad // ns
        self._val_dtypes = {f: np.dtype(dt) for f, dt in val_dtypes.items()}
        self._val_fields = list(self._val_dtypes)
        self._sharding = NamedSharding(self._mesh, P(MESH_AXES))
        self._gpos_dev = jax.device_put(
            np.arange(self._GB, dtype=np.int32), self._sharding)
        self._step_bytes = self._GB * (8 + sum(
            dt.itemsize for dt in self._val_dtypes.values()))
        self.stats.mesh_devices = ns
        self._after_mesh_ensure()

    def _note_degraded(self, requested: int, ns: int) -> None:
        """Degraded-capacity report: the mesh came up on fewer devices
        than the op would otherwise use because the supervision plane
        excluded lost devices (mesh/core registry). Surfaced per-replica
        as ``Mesh_degraded_devices`` plus a ``mesh:degrade`` flight span;
        the supervisor aggregates it into ``Recovery_degraded_devices``
        and the overload governor jumps straight to SHED while > 0."""
        import jax

        from .core import excluded_device_ids

        excl = excluded_device_ids()
        if not excl:
            self.stats.mesh_degraded = 0
            return
        want = min(int(requested), len(jax.devices()))
        degraded = max(0, want - int(ns))
        self.stats.mesh_degraded = degraded
        if degraded:
            from ..monitoring.flightrec import thread_recorder
            rec = thread_recorder()
            if rec is not None:
                rec.event("mesh:degrade", 0.0, {
                    "op": self.op.name, "devices": ns,
                    "excluded": sorted(excl), "requested": want})

    def _after_mesh_ensure(self) -> None:
        raise NotImplementedError

    def _ensure(self, batch: BatchTPU) -> None:
        if self._mesh is None:
            self._mesh_ensure(
                {f: batch.schema.fields[f] for f in batch.fields},
                batch.capacity)

    # -- per-batch key plane --------------------------------------------
    def _batch_slots(self, batch: BatchTPU):
        n = batch.size
        keys = np.asarray(self.batch_keys(batch))[:n]
        if keys.dtype.kind not in "iu":
            raise WindFlowError(
                f"{self.op.name}: mesh operators require integer keys "
                f"(sparse/negative int64 ok); got dtype {keys.dtype}")
        if self._tier is not None and n:
            # tier pre-pass: the mesh replica commits synchronously (no
            # deferred dispatch), so the batched promote/demote applies
            # inline before the slot resolution
            plan = self._tier.plan_batch(
                self._keymap, [int(k) for k in np.unique(keys)])
            if plan is not None:
                self._apply_tier_plan(plan)
            self._tier.publish_gauges(len(self._keymap))
        slots = np.asarray(self._keymap.slots_of(keys, keys, n),
                           dtype=np.int64)
        from .core import mesh_occupancy
        occ, skew = mesh_occupancy(len(self._keymap), self._k_local,
                                   self._ns)
        self.stats.mesh_shard_occupancy = occ
        self.stats.mesh_shard_skew = skew
        return slots, keys

    def _pad_slice(self, slots, cols, lo: int, hi: int):
        """One GB-sized padded slice: slot = -1 lanes mark padding (the
        routing drops them), value columns zero-fill."""
        import jax

        GB = self._GB
        m = hi - lo
        s_sl = np.full(GB, -1, np.int32)
        s_sl[:m] = slots[lo:hi]
        v_sl = {}
        for f in self._val_fields:
            buf = np.zeros(GB, self._val_dtypes[f])
            buf[:m] = cols[f][lo:hi]
            v_sl[f] = jax.device_put(buf, self._sharding)
        return jax.device_put(s_sl, self._sharding), v_sl

    # -- snapshot/restore scaffolding -----------------------------------
    _STATE_KEY = "mesh_state"

    def _snapshot_extra(self) -> dict:
        return {}

    def _device_state_shards(self) -> Optional[list]:
        return None

    def snapshot_state(self) -> dict:
        st = super().snapshot_state()  # drains the dispatch queue
        if self._mesh is None:
            if self._pending_restore is not None:
                # restored but never touched since: pass the blob through
                st[self._STATE_KEY] = self._pending_restore
            return st
        t0 = time.perf_counter()
        d = {
            "slot_of_key": dict(self._keymap.slot_of_key),
            "key_by_slot": self._key_by_slot.copy(),
            "key_capacity": self.op.key_capacity,
            "K_pad": self._K_pad, "n_shards": self._ns,
            "local_batch": self._local_batch,
            "val_dtypes": {f: dt.str
                           for f, dt in self._val_dtypes.items()},
            # per-shard blobs gathered under this one manifest entry
            "table_shards": self._device_state_shards(),
        }
        d.update(self._snapshot_extra())
        st[self._STATE_KEY] = d
        rec = self.stats.recorder
        if rec is not None:
            rec.event("mesh:snapshot",
                      (time.perf_counter() - t0) * 1e6,
                      {"keys": len(self._keymap.slot_of_key),
                       "shards": self._ns})
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        d = state.get(self._STATE_KEY)
        if d is not None:
            # applied lazily once the mesh exists (_ensure): the target
            # mesh factorization may differ from the checkpointed one
            self._pending_restore = d

    def _restore_keymap(self, d: dict) -> None:
        op = self.op
        if len(d["slot_of_key"]) > op.key_capacity:
            raise KeyCapacityError(
                op.name, self._K_pad or op.key_capacity,
                len(d["slot_of_key"]) - op.key_capacity,
                hint="restore holds more distinct keys than this graph's "
                     "key_capacity; raise with_mesh(key_capacity=) to at "
                     "least the checkpointed count")
        self._keymap.slot_of_key.clear()
        self._keymap.slot_of_key.update(d["slot_of_key"])
        self._keymap._lut = None
        kbs = np.asarray(d["key_by_slot"])
        self._key_by_slot[:] = 0
        n_copy = min(len(kbs), op.key_capacity)
        self._key_by_slot[:n_copy] = kbs[:n_copy]


class _MeshScanReplicaBase(_MeshReplicaBase):
    """Stateful Map/Filter over the mesh: the grid-scan table
    block-sharded along the slot axis; one sharded step per GB slice."""

    filter_mode = False
    _STATE_KEY = "mesh_scan"

    def __init__(self, op, idx) -> None:
        super().__init__(op, idx)
        self._table = None
        self._out_schema: Optional[TupleSchema] = None
        # incremental checkpointing (WF_CKPT_DELTA): host-side dirty
        # slot set — each batch and each tier promotion marks the global
        # slot rows it rewrites, so a delta snapshot ships per-shard
        # row patches instead of the whole sharded table
        self._ckpt_dirty: set = set()
        self._delta_base = None  # epoch id of the last full snapshot
        self._snaps_since_full = 0
        self._base_nkeys = None  # key count at the last full snapshot
        self._base_geom = None  # (K_pad, n_shards) at the last full
        cfg = getattr(op, "tiering", None)
        if cfg is not None:
            if cfg.hot_capacity > op.key_capacity:
                raise WindFlowError(
                    f"{op.name}: with_tiering(hot_capacity="
                    f"{cfg.hot_capacity}) exceeds with_mesh(key_capacity="
                    f"{op.key_capacity}) — the mesh table IS the hot "
                    "tier; raise key_capacity or lower hot_capacity")
            from ..state.tiered import TieredKeyStore
            self._tier = TieredKeyStore(f"{op.name}_mesh_tier", cfg,
                                        stats=self.stats)

    @property
    def functor(self) -> Callable:
        raise NotImplementedError

    def _apply_tier_plan(self, plan) -> None:
        """Batched tier movement against the SHARDED table: one slot-row
        gather per leaf feeds the cold writes, one scatter per leaf lands
        the promotions (re-pinned to the mesh sharding — an eager
        scatter's output sharding is XLA's choice, the table's is not)."""
        import jax
        import jax.numpy as jnp

        tier = self._tier
        t0 = time.perf_counter()
        leaves, treedef = jax.tree_util.tree_flatten(self._table)
        if len(plan.demote_keys):
            dslots = jnp.asarray(plan.demote_slots)
            cols = [np.asarray(jax.device_get(lf[dslots]))
                    for lf in leaves]
            tier.cold.put_rows(plan.demote_keys, cols)
            tier.note_demote(len(plan.demote_keys))
        if len(plan.promote_keys):
            init_leaves = jax.tree_util.tree_leaves(self.op.state_init)
            cols, _hits = tier.cold.take_rows(
                plan.promote_keys, init_leaves,
                [np.dtype(lf.dtype) for lf in leaves])
            pslots = jnp.asarray(plan.promote_slots)
            leaves = [jax.device_put(
                          lf.at[pslots].set(jnp.asarray(col)),
                          self._sharding)
                      for lf, col in zip(leaves, cols)]
            self._table = jax.tree_util.tree_unflatten(treedef, leaves)
            for k, s in zip(plan.promote_keys, plan.promote_slots):
                self._key_by_slot[int(s)] = k
            self._ckpt_dirty.update(int(s) for s in plan.promote_slots)
            tier.note_promote(len(plan.promote_keys),
                              (time.perf_counter() - t0) * 1e6)

    def _after_mesh_ensure(self) -> None:
        import jax

        from .core import make_mesh_table

        op = self.op
        self._table = make_mesh_table(self._mesh, op.state_init,
                                      self._K_pad)
        if not self.filter_mode:
            sample_row = {f: jax.ShapeDtypeStruct((), dt)
                          for f, dt in self._val_dtypes.items()}
            state_abs = jax.tree_util.tree_map(
                lambda v: jax.ShapeDtypeStruct(
                    np.shape(v), np.asarray(v).dtype), op.state_init)
            out_shapes, _ = jax.eval_shape(self.functor, sample_row,
                                           state_abs)
            self._out_schema = TupleSchema(
                {f: np.dtype(s.dtype) for f, s in out_shapes.items()})
        if self._pending_restore is not None:
            self._apply_pending_restore()

    def _program(self, M: int):
        from .core import sharded_grid_scan
        op = self.op
        return cached_compile(
            op._scan_prog_cache, op._scan_prog_lock,
            ("mesh", M, self._GB),
            lambda: sharded_grid_scan(self._mesh, self.functor,
                                      self.filter_mode, op.key_capacity,
                                      M, self._local_batch)[0])

    # -- streaming ------------------------------------------------------
    def process_device_batch(self, batch: BatchTPU) -> None:
        self._ensure(batch)
        n = batch.size
        if n == 0:
            return
        slots, keys_raw = self._batch_slots(batch)
        from ..checkpoint.delta import env_ckpt_delta
        if env_ckpt_delta():
            # every slot row this batch scans through is dirty vs base
            self._ckpt_dirty.update(np.unique(slots).tolist())
        cols = {f: np.asarray(batch.fields[f])[:n]
                for f in self._val_fields}
        ts = np.asarray(batch.ts_host[:n])
        GB = self._GB
        for lo in range(0, n, GB):
            hi = min(lo + GB, n)
            cnt = np.bincount(slots[lo:hi],
                              minlength=1) if hi > lo else np.zeros(1)
            mx = max(1, int(cnt.max()))
            M = 1
            while M < mx:
                M <<= 1
            prog = self._program(M)
            s_dev, v_sl = self._pad_slice(slots, cols, lo, hi)
            t0 = time.perf_counter()
            table2, out, _n_ok = prog(self._table, s_dev,
                                      self._gpos_dev, v_sl)
            self._table = table2
            self.stats.device_programs_run += 1
            self.stats.note_mesh_step(
                (time.perf_counter() - t0) * 1e6, self._step_bytes)
            self._emit_slice(batch, out, ts, keys_raw, lo, hi)

    def _emit_slice(self, batch, out, ts, keys_raw, lo, hi) -> None:
        raise NotImplementedError

    # -- compile-stability pre-warm -------------------------------------
    def prewarm(self, caps) -> Optional[int]:
        """Compile the mesh step's small-M bucket signatures on
        all-padding slices (state untouched: every lane is dropped by
        the routing). The per-key-depth axis M is runtime cardinality,
        so deeper batches still trace on demand — but the M=1/2/4
        buckets cover the common keyed-stream shapes. None when the
        schema is inferred at the staging boundary."""
        sch = self.op.schema
        if sch is None:
            return None
        import jax

        if self._mesh is None:
            self._mesh_ensure(dict(sch.fields), max(caps))
        warmed = 0
        for M in (1, 2, 4):
            prog = self._program(M)
            s_dev = jax.device_put(np.full(self._GB, -1, np.int32),
                                   self._sharding)
            v_sl = {f: jax.device_put(np.zeros(self._GB, dt),
                                      self._sharding)
                    for f, dt in self._val_dtypes.items()}
            out = prog(self._table, s_dev, self._gpos_dev, v_sl)
            self._table = out[0]
            jax.block_until_ready(
                jax.tree_util.tree_leaves(self._table)[0])
            warmed += 1
        return warmed

    # -- sharded fault tolerance ----------------------------------------
    def snapshot_state(self) -> dict:
        from ..checkpoint import delta as ckpt_delta

        ctx = ckpt_delta.snapshot_ctx()
        if (self._mesh is not None and self._table is not None
                and self._base_geom == (self._K_pad, self._ns)
                and ckpt_delta.delta_eligible(
                    self._delta_base, self._snaps_since_full, ctx)):
            # DELTA: the TPUReplicaBase part (drain + generic fields)
            # still captures fully; only the mesh_scan entry shrinks to
            # per-shard patches of the dirty slot rows
            st = TPUReplicaBase.snapshot_state(self)
            self._snaps_since_full += 1
            st[self._STATE_KEY] = self._snapshot_mesh_delta()
            return st
        st = super().snapshot_state()
        if (ctx is not None and ckpt_delta.env_ckpt_delta()
                and self._mesh is not None and self._table is not None):
            # this full capture is the new delta baseline
            self._delta_base = ctx.ckpt_id
            self._base_geom = (self._K_pad, self._ns)
            self._base_nkeys = len(self._keymap.slot_of_key)
            self._snaps_since_full = 0
            self._ckpt_dirty = set()
            if self._tier is not None:
                self._tier.wal_reset()
        return st

    def _snapshot_mesh_delta(self) -> dict:
        """Delta against the last full snapshot: ONE cross-shard gather
        of the dirty slot rows, split into per-shard local-row patches
        (shard s owns global rows [s*k_local, (s+1)*k_local))."""
        import jax
        import jax.numpy as jnp

        from ..checkpoint import delta as ckpt_delta

        sl = np.asarray(sorted(self._ckpt_dirty), dtype=np.int64)
        kl = self._k_local
        leaves, _ = jax.tree_util.tree_flatten(self._table)
        jsl = jnp.asarray(sl)
        rows = [np.asarray(jax.device_get(lf[jsl])) for lf in leaves]
        shard_of = sl // kl if len(sl) else sl
        patches: List[Optional[dict]] = []
        for s in range(self._ns):
            m = shard_of == s
            if not len(sl) or not m.any():
                patches.append(None)
                continue
            patches.append({"slots": sl[m] - s * kl,
                            "leaves": [r[m] for r in rows]})
        repl = {"key_capacity": self.op.key_capacity,
                "K_pad": self._K_pad, "n_shards": self._ns,
                "local_batch": self._local_batch,
                "val_dtypes": {f: dt.str
                               for f, dt in self._val_dtypes.items()}}
        rows = {}
        carry = []
        if (self._tier is None
                and len(self._keymap.slot_of_key) == self._base_nkeys):
            # no key registered since the base: the directory (and its
            # device twin by-slot column) is a zero-byte carry. Slots
            # are append-only without tiering; tier swaps remap at
            # constant size, so never carry there.
            carry += ["slot_of_key", "key_by_slot"]
        else:
            repl["slot_of_key"] = dict(self._keymap.slot_of_key)
            rows["key_by_slot"] = {
                "slots": sl, "leaves": [self._key_by_slot[sl].copy()]}
        node = ckpt_delta.make_delta(
            self._delta_base, rows=rows or None,
            shards={"table_shards": patches},
            replace=repl, carry=carry or None)
        if self._tier is not None:
            node["replace"]["tier"] = self._tier.snapshot_delta(
                self._delta_base)
        return node

    def restore_state(self, state: dict) -> None:
        # restored state starts a fresh delta lineage
        self._ckpt_dirty = set()
        self._delta_base = None
        self._snaps_since_full = 0
        self._base_geom = None
        self._base_nkeys = None
        super().restore_state(state)

    def _snapshot_extra(self) -> dict:
        if self._tier is None:
            return {}
        import jax

        from ..state.tiered import hot_table_digest

        host = (None if self._table is None
                else jax.device_get(self._table))
        return {"tier": self._tier.snapshot(
            hot_digest=hot_table_digest(host))}

    def _device_state_shards(self) -> Optional[list]:
        if self._table is None:
            return None
        import jax

        tmap = jax.tree_util.tree_map
        host = tmap(lambda a: np.ascontiguousarray(
            np.asarray(jax.device_get(a))), self._table)
        kl = self._k_local
        return [tmap(lambda a, _s=s: a[_s * kl:(_s + 1) * kl], host)
                for s in range(self._ns)]

    def _apply_pending_restore(self) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .core import MESH_AXES

        t0 = time.perf_counter()
        d, self._pending_restore = self._pending_restore, None
        tier_blob = d.get("tier")
        if tier_blob is not None and self._tier is None:
            raise WindFlowError(
                f"{self.op.name}: checkpoint holds a TIERED key store "
                "but this graph was built without with_tiering(); "
                "cold-tier keys cannot restore into a dense mesh table")
        self._restore_keymap(d)
        if self._tier is not None:
            if tier_blob is not None:
                from ..state.tiered import hot_table_digest
                shards_ = d.get("table_shards")
                full_ = (None if shards_ is None else jax.tree_util.tree_map(
                    lambda *parts: np.concatenate(parts, axis=0), *shards_))
                self._tier.restore(tier_blob,
                                   hot_digest=hot_table_digest(full_))
            else:
                # dense mesh checkpoint into a tiered graph: adopt every
                # checkpointed key as hot (refused when they don't fit)
                self._tier.adopt_dense(self._keymap.slot_of_key)
        shards = d.get("table_shards")
        if shards is None:
            return
        tmap = jax.tree_util.tree_map
        full = tmap(lambda *parts: np.concatenate(parts, axis=0), *shards)
        K_new = self._K_pad

        def fit(leaf, init_leaf):
            leaf = np.asarray(leaf)
            out = np.empty((K_new,) + leaf.shape[1:], dtype=leaf.dtype)
            out[:] = np.asarray(init_leaf, dtype=leaf.dtype)
            rows = min(leaf.shape[0], K_new)
            out[:rows] = leaf[:rows]
            return out

        sh = NamedSharding(self._mesh, P(MESH_AXES))
        self._table = tmap(
            lambda l, i: jax.device_put(fit(l, i), sh),
            full, self.op.state_init)
        rec = self.stats.recorder
        if rec is not None:
            rec.event("mesh:restore",
                      (time.perf_counter() - t0) * 1e6,
                      {"keys": len(self._keymap.slot_of_key),
                       "K_pad": K_new})


class MapMeshReplica(_MeshScanReplicaBase):
    filter_mode = False

    @property
    def functor(self) -> Callable:
        return self.op.func

    def _emit_slice(self, batch, out, ts, keys_raw, lo, hi) -> None:
        GB = self._GB
        m = hi - lo
        ts2 = np.zeros(GB, np.int64)
        ts2[:m] = ts[lo:hi]
        nb = BatchTPU(dict(out), ts2, m, self._out_schema, batch.wm,
                      keys_raw[lo:hi].tolist())
        nb.stream_tag = batch.stream_tag
        nb.copy_trace_from(batch)
        self._emit_batch(nb)


class FilterMeshReplica(_MeshScanReplicaBase):
    filter_mode = True

    @property
    def functor(self) -> Callable:
        return self.op.pred

    def _emit_slice(self, batch, out, ts, keys_raw, lo, hi) -> None:
        import jax

        m = hi - lo
        keep = np.asarray(out)[:m].astype(bool)
        kept = np.nonzero(keep)[0]
        self.stats.inputs_ignored += m - len(kept)
        if not len(kept):
            return
        cap = bucket_capacity(len(kept))
        sel = np.zeros(cap, np.int32)
        sel[:len(kept)] = lo + kept  # rows of the ORIGINAL device batch
        sel_dev = jax.device_put(sel)
        out_fields = {f: batch.fields[f][sel_dev] for f in batch.fields}
        ts2 = np.zeros(cap, np.int64)
        ts2[:len(kept)] = ts[lo:hi][kept]
        nb = BatchTPU(out_fields, ts2, len(kept), batch.schema, batch.wm,
                      keys_raw[lo:hi][kept].tolist())
        nb.stream_tag = batch.stream_tag
        nb.copy_trace_from(batch)
        self._emit_batch(nb)


class ReduceMeshReplica(_MeshReplicaBase):
    """Keyed per-batch reduce: shuffle + segmented combine on device,
    per-slot results harvested to one output row per distinct key."""

    _STATE_KEY = "mesh_reduce"

    def __init__(self, op, idx) -> None:
        super().__init__(op, idx)
        self._step = None

    def _after_mesh_ensure(self) -> None:
        from .core import sharded_keyed_reduce
        self._step = sharded_keyed_reduce(
            self._mesh, self.op.combine, self.op.key_capacity,
            self._local_batch)[0]
        if self._pending_restore is not None:
            self._restore_keymap(self._pending_restore)
            self._pending_restore = None

    def _host_combine(self, a: dict, b: dict) -> dict:
        """Cross-slice merge (only when one batch spans several GB
        slices): the user combine over host scalars; fields it does not
        return pass through unchanged."""
        merged = self.op.combine(a, b)
        return {f: np.asarray(merged[f]).astype(self._val_dtypes[f])
                if f in merged else b[f] for f in b}

    def process_device_batch(self, batch: BatchTPU) -> None:
        self._ensure(batch)
        n = batch.size
        if n == 0:
            return
        import jax  # noqa: F401  (device plane active past this point)

        slots, keys_raw = self._batch_slots(batch)
        cols = {f: np.asarray(batch.fields[f])[:n]
                for f in self._val_fields}
        acc: Dict[int, dict] = {}
        GB = self._GB
        for lo in range(0, n, GB):
            hi = min(lo + GB, n)
            s_dev, v_sl = self._pad_slice(slots, cols, lo, hi)
            t0 = time.perf_counter()
            res, touched, _n_ok = self._step(s_dev, v_sl)
            self.stats.device_programs_run += 1
            self.stats.note_mesh_step(
                (time.perf_counter() - t0) * 1e6, self._step_bytes)
            touched_np = np.asarray(touched)
            res_np = {f: np.asarray(v) for f, v in res.items()}
            for s in np.nonzero(touched_np)[0]:
                row = {f: res_np[f][s] for f in res_np}
                s = int(s)
                acc[s] = row if s not in acc \
                    else self._host_combine(acc[s], row)
        if not acc:
            return
        self._emit_rows(batch, acc, ts_max=int(np.asarray(
            batch.ts_host[:n]).max()))

    def _emit_rows(self, batch, acc: Dict[int, dict], ts_max: int) -> None:
        import jax

        out_slots = sorted(acc)
        n_out = len(out_slots)
        cap = bucket_capacity(n_out)
        out_fields = {}
        for f in self._val_fields:
            buf = np.zeros(cap, self._val_dtypes[f])
            buf[:n_out] = [acc[s][f] for s in out_slots]
            out_fields[f] = jax.device_put(buf)
        ts2 = np.full(cap, ts_max, np.int64)
        keys2 = [int(self._key_by_slot[s]) for s in out_slots]
        nb = BatchTPU(out_fields, ts2, n_out, batch.schema, batch.wm,
                      keys2)
        nb.stream_tag = batch.stream_tag
        nb.copy_trace_from(batch)
        self._emit_batch(nb)

    # -- compile-stability pre-warm -------------------------------------
    def prewarm(self, caps) -> Optional[int]:
        """The keyed-reduce mesh step has ONE signature per graph (the
        GB padding makes every batch identical in shape): compile it on
        an all-padding slice. None when the schema is inferred."""
        sch = self.op.schema
        if sch is None:
            return None
        import jax

        if self._mesh is None:
            self._mesh_ensure(dict(sch.fields), max(caps))
        s_dev = jax.device_put(np.full(self._GB, -1, np.int32),
                               self._sharding)
        v_sl = {f: jax.device_put(np.zeros(self._GB, dt), self._sharding)
                for f, dt in self._val_dtypes.items()}
        out = self._step(s_dev, v_sl)
        jax.block_until_ready(out[1])
        return 1
