"""Core enums, constants and small helpers.

Parity notes (reference = ParaGroup/WindFlow, read-only at /root/reference):
- Execution modes / time policies / window types / routing modes mirror the
  enums in ``wf/basic.hpp:78-93``.
- Watermark cadence knobs mirror ``wf/basic.hpp:199-216`` (default punctuation
  interval 100 ms).
- Default channel capacity mirrors FastFlow's ``DEFAULT_BUFFER_CAPACITY``
  (2048) used for the bounded inter-replica queues.

This module is dependency-free (no jax import) so the pure-CPU plane never
pays device-plane import cost.
"""

from __future__ import annotations

import enum
import time


class ExecutionMode(enum.Enum):
    """How out-of-order input is handled (``wf/basic.hpp:78-82``)."""

    DEFAULT = "default"  # watermark-based (Watermark_Collector)
    DETERMINISTIC = "deterministic"  # total order merge (Ordering_Collector)
    PROBABILISTIC = "probabilistic"  # K-slack reordering (KSlack_Collector)


class TimePolicy(enum.Enum):
    """Where timestamps come from (``wf/basic.hpp:85-88``)."""

    INGRESS_TIME = "ingress_time"  # assigned by the source shipper at push
    EVENT_TIME = "event_time"  # provided by the user with the tuple


class WinType(enum.Enum):
    """Window semantics (``wf/basic.hpp:91-93``)."""

    CB = "count_based"
    TB = "time_based"


class RoutingMode(enum.Enum):
    """Distribution policy of an operator's input (``wf/basic.hpp:232`` area)."""

    NONE = "none"
    FORWARD = "forward"
    KEYBY = "keyby"
    BROADCAST = "broadcast"
    REBALANCING = "rebalancing"


class OpType(enum.Enum):
    """Coarse operator classification used by topology checks."""

    SOURCE = "source"
    BASIC = "basic"
    WIN = "win"
    JOIN = "join"
    SINK = "sink"
    TPU = "tpu"
    WIN_TPU = "win_tpu"


class JoinMode(enum.Enum):
    """Interval join parallelism (``wf/interval_join.hpp``): KP = key
    partitioning, DP = data parallelism inside each key."""

    NONE = "none"
    KP = "key_parallel"
    DP = "data_parallel"


class WinRole(enum.Enum):
    """Role of a window replica inside composed window operators
    (``wf/parallel_windows.hpp:120,267``)."""

    SEQ = "seq"
    PLQ = "plq"
    WLQ = "wlq"
    MAP = "map"
    REDUCE = "reduce"


# --- watermark / punctuation cadence (wf/basic.hpp:199-216) -----------------
DEFAULT_WM_INTERVAL_USEC = 100_000  # punctuation cadence: 100 ms
DEFAULT_WM_AMOUNT = 64  # check elapsed time once every N emitted tuples

# --- queue capacity (FastFlow DEFAULT_BUFFER_CAPACITY) ----------------------
DEFAULT_BUFFER_CAPACITY = 2048

# --- device batching --------------------------------------------------------
DEFAULT_OUTPUT_BATCH_SIZE = 0  # 0 => Single_t-style per-tuple messages


def current_time_usecs() -> int:
    """Microseconds from an arbitrary monotonic origin (reference uses
    microseconds from epoch; only differences matter)."""
    return time.monotonic_ns() // 1_000


_MISSING = object()


def env_flag(name: str) -> bool:
    """Consistent boolean env semantics: '1'/'true'/'yes'/'on' enable."""
    import os
    return os.environ.get(name, "").strip().lower() in ("1", "true", "yes",
                                                        "on")


def identity(x):
    return x


class WindFlowError(RuntimeError):
    """Topology / runtime error. The reference prints a colored message and
    ``exit(EXIT_FAILURE)``; we raise instead so tests can assert on misuse."""


class KeyCapacityError(WindFlowError):
    """A keyed device structure refused new keys: the distinct-key count
    exceeded the declared dense capacity (``K_pad`` — the padded slot
    count of the device table). Typed so callers can tell "grow the
    capacity / enable tiering" apart from generic topology errors, and
    carries the operator, the padded capacity, and how many keys were
    refused. This stays the loud failure mode when tiering is NOT
    enabled; ``with_tiering(...)`` makes the capacity elastic instead."""

    def __init__(self, op_name: str, k_pad: int, refused: int,
                 hint: str = "") -> None:
        self.op_name = op_name
        self.k_pad = int(k_pad)
        self.refused = int(refused)
        msg = (f"{op_name}: {self.refused} new key(s) refused — distinct "
               f"key count exceeds the device key capacity K_pad="
               f"{self.k_pad}")
        if hint:
            msg += f"; {hint}"
        super().__init__(msg)


class RescaleTeardown(BaseException):
    """Internal control-flow signal of the elastic-rescale plane
    (``windflow_tpu.scaling``): a worker parked at a rescale barrier is
    told to unwind WITHOUT the EOS cascade — its channels and emitters
    are about to be rebuilt at the new parallelism. BaseException so user
    functors' ``except Exception`` handlers cannot swallow it mid-source;
    ``Worker.run`` catches it explicitly and exits silently."""


class SupervisorTeardown(RescaleTeardown):
    """Supervised-recovery twin of ``RescaleTeardown``
    (``windflow_tpu.supervision``): raised out of a CLOSED channel's
    put/get so every worker of a dying runtime plane — sources blocked
    mid-push included — unwinds promptly without an EOS cascade while
    the supervisor rebuilds and restores from the latest committed
    checkpoint. Subclasses RescaleTeardown so the worker's silent-exit
    path handles both."""


class WorkerFailuresError(WindFlowError):
    """Aggregate of SEVERAL workers' errors (``PipeGraph.wait_end``): a
    single dead worker re-raises its own exception unchanged, but when
    multiple workers died the message names every one of them instead of
    silently discarding all but ``errors[0]``. ``worker_errors`` maps
    worker name -> exception; ``__cause__`` is the first error."""

    def __init__(self, worker_errors) -> None:
        self.worker_errors = dict(worker_errors)
        parts = [f"{name} ({type(e).__name__}: {e})"
                 for name, e in self.worker_errors.items()]
        super().__init__(
            f"{len(self.worker_errors)} workers died: " + "; ".join(parts))


def as_key_fn(key):
    """Normalize a key extractor: callables pass through; a string names a
    tuple field (works for dataclass attributes and dict keys). String keys
    are preferred for TPU operators — the key is then a device column and
    keyed re-shards never need host tuple objects."""
    if key is None or callable(key):
        return key

    if isinstance(key, str):
        def field_key(payload, _name=key):
            if isinstance(payload, dict):
                return payload[_name]
            return getattr(payload, _name)
        return field_key
    names = key_fields_names(key)
    if names is not None:
        def fields_key(payload, _names=names):
            if isinstance(payload, dict):
                return tuple(payload[f] for f in _names)
            return tuple(getattr(payload, f) for f in _names)
        return fields_key
    raise WindFlowError(f"invalid key extractor: {key!r}")


def key_field_name(key):
    """The device column name of a key extractor, or None for callables."""
    return key if isinstance(key, str) else None


def key_fields_names(key):
    """The device column names of a COMPOSITE key extractor (a tuple/list
    of field names, e.g. ``("campaign", "ad")`` — the YSB join key shape),
    or None. Composite keys extract as tuples on the row path; the device
    plane routes them as stacked columns with no per-row Python
    (reference: ``wf/keyby_emitter.hpp:210-228`` hashes any key_t at O(1)
    C++ cost — here the vectorized column fold is the equivalent).
    Datetime key fields: ROUTING is consistent across paths, but a
    stream mixing push() and push_columns() should carry
    datetime.date/datetime payload values (what numpy 'M8' columns
    materialize to), not np.datetime64 scalars — the latter hash
    differently as DICT keys and would register duplicate key slots."""
    if isinstance(key, (tuple, list)) and key \
            and all(isinstance(f, str) for f in key):
        names = tuple(key)
        if len(set(names)) != len(names):
            # fail at with_key_by()/build time: the columnar path would
            # otherwise crash mid-stream in the structured-dtype build
            raise WindFlowError(
                f"composite key repeats a field name: {names}")
        return names
    return None
