"""RestartPolicy: when and how fast the supervisor restarts the graph.

The policy is pure decision logic (no threads): the supervisor asks it
for the next backoff delay and whether another restart fits the budget.
Restarts are counted inside a sliding window — a graph that crashes
steadily burns through the budget and escalates, while one that crashed
once a week ago restarts with a fresh budget and minimal backoff.
"""

from __future__ import annotations

import os
import random
import time
from typing import List, Optional


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default  # malformed knob must not take down the graph


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class RestartPolicy:
    """Jittered exponential backoff + a bounded restart budget.

    ``max_restarts`` restarts are allowed per sliding ``window_s``
    window; one more failure escalates (the supervisor gives up and the
    aggregated error surfaces in ``wait_end``). The k-th consecutive
    restart waits ``backoff_s * factor**k`` seconds, capped at
    ``backoff_max_s``, with uniform jitter in ``[1-jitter, 1]`` of that
    value so a fleet of supervised graphs never thunders in lockstep.
    A stretch of ``window_s`` without failures resets the consecutive
    counter (the backoff re-anchors at ``backoff_s``).

    Env twins (read by :meth:`from_env`): ``WF_SUPERVISE_MAX_RESTARTS``,
    ``WF_SUPERVISE_WINDOW_S``, ``WF_SUPERVISE_BACKOFF_S``,
    ``WF_SUPERVISE_BACKOFF_MAX_S``.
    """

    def __init__(self, max_restarts: int = 5, window_s: float = 300.0,
                 backoff_s: float = 0.5, backoff_max_s: float = 30.0,
                 backoff_factor: float = 2.0, jitter: float = 0.5,
                 restart_on_stall: bool = True,
                 seed: Optional[int] = None) -> None:
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        self.max_restarts = int(max_restarts)
        self.window_s = float(window_s)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.backoff_factor = float(backoff_factor)
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        # stall-watchdog episodes count as failures (the wedged worker
        # thread is abandoned — Python threads cannot be killed — and
        # the runtime plane is rebuilt around it)
        self.restart_on_stall = bool(restart_on_stall)
        self._rng = random.Random(seed)
        self._restarts: List[float] = []  # monotonic stamps, in-window

    @classmethod
    def from_env(cls) -> "RestartPolicy":
        return cls(
            max_restarts=_env_int("WF_SUPERVISE_MAX_RESTARTS", 5),
            window_s=_env_float("WF_SUPERVISE_WINDOW_S", 300.0),
            backoff_s=_env_float("WF_SUPERVISE_BACKOFF_S", 0.5),
            backoff_max_s=_env_float("WF_SUPERVISE_BACKOFF_MAX_S", 30.0))

    # -- budget ------------------------------------------------------------
    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        self._restarts = [t for t in self._restarts if t >= cutoff]

    def allow_restart(self, now: Optional[float] = None) -> bool:
        """True when one more restart fits the in-window budget."""
        now = time.monotonic() if now is None else now
        self._prune(now)
        return len(self._restarts) < self.max_restarts

    def note_restart(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._restarts.append(now)

    @property
    def consecutive(self) -> int:
        """Restarts currently inside the window (drives the backoff
        exponent; an idle window resets it)."""
        self._prune(time.monotonic())
        return len(self._restarts)

    # -- backoff -----------------------------------------------------------
    def next_backoff(self, now: Optional[float] = None) -> float:
        """Jittered delay before the NEXT restart attempt (seconds)."""
        now = time.monotonic() if now is None else now
        self._prune(now)
        k = len(self._restarts)
        base = min(self.backoff_s * (self.backoff_factor ** k),
                   self.backoff_max_s)
        lo = base * (1.0 - self.jitter)
        return lo + self._rng.random() * (base - lo)
