"""Per-record failure containment: error policies + the dead-letter queue.

The default (``ErrorPolicy.FAIL``) is the pre-existing behavior — a
functor exception kills the worker and (without supervision) the graph.
Any other policy wraps functor invocation so one poison tuple no longer
takes the pipeline down:

- ``SKIP``       — drop the record, count it (``Dlq_skipped``);
- ``RETRY(n)``   — re-invoke with exponential backoff, then apply the
                   ``on_exhausted`` fallback (default ``dead_letter``);
- ``DEAD_LETTER``— quarantine record + exception metadata into the
                   graph's :class:`DeadLetterQueue` (``Dlq_records``).

Host path: ``BasicReplica`` swaps its ``process`` for a guarded wrapper
at construction (instance attribute — the FAIL default pays nothing).
Device path: whole batches run one XLA program, so a failing batch is
BISECTED — each half re-runs until the offending record is isolated at
size 1 and the policy applies to that single record (the batch-splitting
analog of per-tuple wrapping; see ``TPUReplicaBase.handle_msg``).

Only ``Exception`` is contained: ``BaseException`` control-flow signals
(``RescaleTeardown``/``SupervisorTeardown``, KeyboardInterrupt) always
propagate.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from ..basic import WindFlowError

_KINDS = ("fail", "skip", "retry", "dead_letter")


class ErrorPolicy:
    """Per-operator record-failure policy. Use the factory constructors
    (``ErrorPolicy.FAIL``/``SKIP``/``DEAD_LETTER`` or
    ``ErrorPolicy.RETRY(n, ...)``) rather than ``__init__``."""

    __slots__ = ("kind", "retries", "backoff_s", "backoff_factor",
                 "on_exhausted", "dlq")

    FAIL: "ErrorPolicy"
    SKIP: "ErrorPolicy"
    DEAD_LETTER: "ErrorPolicy"

    def __init__(self, kind: str, retries: int = 0, backoff_s: float = 0.0,
                 backoff_factor: float = 2.0,
                 on_exhausted: str = "dead_letter") -> None:
        if kind not in _KINDS:
            raise WindFlowError(
                f"ErrorPolicy: unknown kind {kind!r} (choose from {_KINDS})")
        if on_exhausted not in ("fail", "skip", "dead_letter"):
            raise WindFlowError(
                f"ErrorPolicy: on_exhausted must be fail/skip/dead_letter, "
                f"got {on_exhausted!r}")
        self.kind = kind
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.on_exhausted = on_exhausted
        # the graph injects its DeadLetterQueue at build time when the
        # policy can dead-letter and none was given explicitly
        self.dlq: Optional["DeadLetterQueue"] = None

    @classmethod
    def RETRY(cls, retries: int, backoff_s: float = 0.01,
              backoff_factor: float = 2.0,
              on_exhausted: str = "dead_letter") -> "ErrorPolicy":
        """Re-invoke the functor up to ``retries`` extra times with
        exponential backoff (``backoff_s * factor**attempt`` sleeps),
        then apply ``on_exhausted`` ("fail" | "skip" | "dead_letter").
        Note: a functor with partial side effects before the raise (a
        FlatMap that pushed some outputs) duplicates them on retry —
        retry suits idempotent/pure functors."""
        if retries < 1:
            raise WindFlowError("ErrorPolicy.RETRY: retries must be >= 1")
        return cls("retry", retries, backoff_s, backoff_factor, on_exhausted)

    @property
    def is_fail(self) -> bool:
        return self.kind == "fail"

    @property
    def may_dead_letter(self) -> bool:
        return self.kind == "dead_letter" or (
            self.kind == "retry" and self.on_exhausted == "dead_letter")

    @classmethod
    def parse(cls, spec: str) -> "ErrorPolicy":
        """Env-knob form (``WF_ERROR_POLICY``): ``fail`` | ``skip`` |
        ``dead_letter`` | ``retry:N``."""
        s = spec.strip().lower()
        if s.startswith("retry"):
            n = int(s.split(":", 1)[1]) if ":" in s else 1
            return cls.RETRY(n)
        return {"fail": cls.FAIL, "skip": cls.SKIP,
                "dead_letter": cls.DEAD_LETTER}.get(s) or cls(s)

    def __repr__(self) -> str:
        if self.kind == "retry":
            return (f"ErrorPolicy.RETRY({self.retries}, "
                    f"on_exhausted={self.on_exhausted!r})")
        return f"ErrorPolicy.{self.kind.upper()}"


ErrorPolicy.FAIL = ErrorPolicy("fail")
ErrorPolicy.SKIP = ErrorPolicy("skip")
ErrorPolicy.DEAD_LETTER = ErrorPolicy("dead_letter")


def _safe_repr(payload: Any, limit: int = 512) -> str:
    try:
        r = repr(payload)
    except Exception:
        r = f"<unreprable {type(payload).__name__}>"
    return r if len(r) <= limit else r[:limit] + "…"


class DeadLetterQueue:
    """Graph-level quarantine side-channel: a bounded in-memory ring of
    dead-letter records (newest kept) plus an optional on-disk JSONL
    stream (``WF_DLQ_DIR``/``dir``: one ``<graph>.dlq.jsonl`` file, one
    JSON object per quarantined record — the durable DLQ a downstream
    re-drive job consumes).

    Record schema (both forms)::

        {"operator": str, "replica": int, "payload": repr, "ts": int,
         "error": "Type: message", "traceback": str, "wall_time": float}

    The in-memory ring additionally keeps the live payload OBJECT under
    ``"payload_obj"`` for same-process inspection/re-injection.

    The bounded-ring + JSONL-stream machinery is reusable: subclasses
    override ``_suffix``/``_env_dir`` and feed ``put_raw`` their own
    record schema (the overload plane's shed audit log,
    ``windflow_tpu.overload.admission.ShedLog``, does exactly that).
    """

    _suffix = ".dlq.jsonl"
    _env_dir = "WF_DLQ_DIR"

    def __init__(self, graph_name: str = "pipegraph", capacity: int = 10_000,
                 dir: Optional[str] = None) -> None:
        self.graph_name = graph_name
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.total = 0  # ever quarantined (the ring may have evicted)
        self._dir = dir if dir is not None else os.environ.get(self._env_dir)
        self._path: Optional[str] = None
        if self._dir:
            safe = "".join(c if c.isalnum() or c in "-_." else "_"
                           for c in graph_name) or "pipegraph"
            self._path = os.path.join(self._dir, f"{safe}{self._suffix}")

    def put_raw(self, rec: Dict[str, Any],
                ring_extra: Optional[Dict[str, Any]] = None) -> None:
        """Append one pre-composed record: ring (plus ``ring_extra``
        in-memory-only keys) and, when a directory is configured, the
        JSONL stream."""
        with self._lock:
            self.total += 1
            self._ring.append(rec if ring_extra is None
                              else {**rec, **ring_extra})
            if self._path is not None:
                self._append_jsonl(rec)

    def put(self, operator: str, replica: int, payload: Any, ts: int,
            exc: BaseException) -> Dict[str, Any]:
        rec = {
            "operator": operator,
            "replica": int(replica),
            "payload": _safe_repr(payload),
            "ts": int(ts),
            "error": f"{type(exc).__name__}: {exc}",
            "traceback": "".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__)),
            "wall_time": time.time(),
        }
        self.put_raw(rec, ring_extra={"payload_obj": payload})
        return rec

    def _append_jsonl(self, rec: Dict[str, Any]) -> None:
        import json
        try:
            os.makedirs(self._dir, exist_ok=True)
            with open(self._path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass  # a full disk must not turn quarantine into a crash

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def path(self) -> Optional[str]:
        return self._path


_DEFAULT_DLQ: Optional[DeadLetterQueue] = None


def _default_dlq() -> DeadLetterQueue:
    """Fallback quarantine for replicas driven outside a PipeGraph."""
    global _DEFAULT_DLQ
    if _DEFAULT_DLQ is None:
        _DEFAULT_DLQ = DeadLetterQueue("standalone")
    return _DEFAULT_DLQ


# ---------------------------------------------------------------------------
# host-path guard (wired by BasicReplica when the policy is not FAIL)
# ---------------------------------------------------------------------------
def apply_record_policy(replica, policy: ErrorPolicy, payload: Any, ts: int,
                        exc: Exception, invoke=None) -> None:
    """One failed record under a non-FAIL policy. ``invoke`` re-runs the
    record for RETRY (None = not retryable in this context: the retry
    budget is charged, then the fallback applies directly)."""
    stats = replica.stats
    kind = policy.kind
    if kind == "retry" and invoke is not None:
        last = exc
        for attempt in range(policy.retries):
            stats.dlq_retries += 1
            delay = policy.backoff_s * (policy.backoff_factor ** attempt)
            if delay > 0:
                time.sleep(delay)
            try:
                invoke()
                return  # healed
            except Exception as e:  # noqa: BLE001 — policy boundary
                last = e
        exc, kind = last, policy.on_exhausted
    elif kind == "retry":
        kind = policy.on_exhausted
    if kind == "fail":
        raise exc
    if kind == "skip":
        stats.dlq_skipped += 1
        stats.inputs_ignored += 1
        return
    # dead_letter — DLQ resolution: the graph injects a per-OP queue at
    # build (op._dlq; never stored on the policy object, which may be
    # the shared DEAD_LETTER singleton), an explicit policy.dlq wins,
    # and replicas driven outside a PipeGraph fall back to a module
    # default so quarantine never crashes
    dlq = getattr(replica.op, "_dlq", None)
    if dlq is None:  # explicit is-None: an EMPTY queue is falsy (__len__)
        dlq = policy.dlq
    if dlq is None:
        dlq = _default_dlq()
    dlq.put(replica.op.name, replica.idx, payload, ts, exc)
    stats.dlq_records += 1
    stats.inputs_ignored += 1
    rec = stats.recorder
    if rec is not None:
        try:
            rec.event("dlq:quarantine", 0.0,
                      {"op": replica.op.name,
                       "error": f"{type(exc).__name__}: {exc}"})
        except Exception:
            pass  # telemetry must never fail the quarantine


def make_guarded_process(replica, policy: ErrorPolicy):
    """The host-path wrapper installed over ``replica.process`` (bound
    subclass method captured once; the wrapper is an instance attribute,
    so operators on the default FAIL policy pay nothing)."""
    raw = replica.process

    def guarded(payload, ts, wm, tag):
        try:
            return raw(payload, ts, wm, tag)
        except Exception as exc:  # noqa: BLE001 — the policy boundary
            apply_record_policy(replica, policy, payload, ts, exc,
                                invoke=lambda: raw(payload, ts, wm, tag))

    return guarded


# ---------------------------------------------------------------------------
# device-path bisection (TPUReplicaBase.handle_msg under a non-FAIL policy)
# ---------------------------------------------------------------------------
def split_batch(batch) -> List[Any]:
    """Bisect a ``BatchTPU`` into two half batches (device column slices
    + matching host metadata) for poison isolation. Slicing device
    arrays stays on-device; per-batch key-slot metadata is dropped (the
    consuming keyed op recomputes it lazily, as it does for any batch)."""
    from ..tpu.batch import BatchTPU

    n = batch.size
    mid = n // 2
    out = []
    for lo, hi in ((0, mid), (mid, n)):
        if hi <= lo:
            continue
        fields = {name: col[lo:hi] for name, col in batch.fields.items()}
        keys = (batch.host_keys[lo:hi] if batch.host_keys is not None
                else None)
        nb = BatchTPU(fields, batch.ts_host[lo:hi], hi - lo, batch.schema,
                      batch.wm, keys)
        nb.stream_tag = batch.stream_tag
        nb.copy_trace_from(batch)
        out.append(nb)
    return out


def batch_row_payload(batch, idx: int = 0) -> Dict[str, Any]:
    """Materialize one row of a device batch as a host dict (the
    dead-letter payload for an isolated poison record)."""
    import numpy as np

    row = {}
    for name, col in batch.fields.items():
        try:
            row[name] = np.asarray(col)[idx].item()
        except Exception:
            row[name] = f"<unreadable column {name}>"
    return row
