"""Device-health probing and failure-domain mapping (device-loss plane).

DrJAX-style sharded execution (PAPERS.md, arXiv:2403.07128) makes the
single device the natural failure domain of the mesh plane: a lost chip
takes out exactly the mesh shards placed on it, nothing else. This
module supplies the two pieces the supervisor needs to act on that:

- a pluggable :class:`DeviceHealthProbe` answering "which device ids are
  dead right now?" — the default :class:`JaxDeviceProbe` runs a tiny
  device_put per device (an unreachable chip raises); tests and the
  chaos harness inject a :class:`StaticDeviceProbe` with a mutable dead
  set to simulate loss and return;
- :func:`failure_domain_map`: device id -> the mesh operators whose
  sharded state lives on it, read from the built replicas' meshes —
  what the ``mesh:degrade`` span reports so an operator knows WHAT a
  dead chip takes down.

The supervisor consults the probe before every rebuild and publishes
the dead set into the mesh-core exclusion registry
(``mesh.core.set_excluded_devices``): the rebuilt mesh ops come up on
the surviving devices, restoring their sharded state through the
existing slot-row-gather relayout (byte-identical keyed results — only
padding rows move). While any device is excluded the graph runs
degraded (``Recovery_degraded_devices`` > 0, overload governor sheds
instead of scaling); when the probe sees the device return, the
supervisor performs one planned restart to re-expand to full shape.

``WF_HEALTH_PROBE=jax`` installs the default probe on supervised graphs
without code changes; ``PipeGraph.with_device_probe`` installs any
probe explicitly. ``WF_HEALTH_PROBE_INTERVAL`` (seconds) paces the
recovery polling.
"""

from __future__ import annotations

import os
from typing import Dict, FrozenSet, Iterable, List

__all__ = ["DeviceHealthProbe", "JaxDeviceProbe", "StaticDeviceProbe",
           "failure_domain_map", "probe_from_env"]


class DeviceHealthProbe:
    """Answers which accelerator device ids are currently dead. The
    supervisor calls :meth:`dead_devices` before every rebuild and every
    ``interval_s`` while the graph runs degraded (re-expansion poll).
    Implementations must be cheap and must never raise for a healthy
    system — a probe exception is treated as "no new information"."""

    interval_s: float = 1.0

    def dead_devices(self) -> FrozenSet[int]:
        raise NotImplementedError


class JaxDeviceProbe(DeviceHealthProbe):
    """Default probe: a scalar ``device_put`` + ``block_until_ready``
    per device — an unreachable/failed chip raises, a healthy one costs
    microseconds. Suitable for the virtual CPU mesh and real TPU
    slices alike."""

    def __init__(self, interval_s: float = 1.0) -> None:
        self.interval_s = float(interval_s)

    def dead_devices(self) -> FrozenSet[int]:
        import jax
        import jax.numpy as jnp

        dead = set()
        for d in jax.devices():
            try:
                jax.device_put(jnp.zeros((), jnp.int32), d) \
                    .block_until_ready()
            except Exception:
                dead.add(int(d.id))
        return frozenset(dead)


class StaticDeviceProbe(DeviceHealthProbe):
    """Test/chaos probe: reports exactly the mutable ``dead`` set, so a
    harness can simulate device loss (``probe.dead.add(7)``) and return
    (``probe.dead.clear()``) without touching jax at all."""

    def __init__(self, dead: Iterable[int] = (),
                 interval_s: float = 0.05) -> None:
        self.dead = set(int(d) for d in dead)
        self.interval_s = float(interval_s)

    def dead_devices(self) -> FrozenSet[int]:
        return frozenset(self.dead)


def probe_from_env() -> "DeviceHealthProbe | None":
    """``WF_HEALTH_PROBE=jax`` -> a :class:`JaxDeviceProbe` (paced by
    ``WF_HEALTH_PROBE_INTERVAL`` seconds, default 1.0); unset/other ->
    None (no probing — device loss then surfaces as worker crashes
    only, recovered without exclusions)."""
    kind = os.environ.get("WF_HEALTH_PROBE", "").strip().lower()
    if kind != "jax":
        return None
    try:
        interval = float(os.environ.get("WF_HEALTH_PROBE_INTERVAL",
                                        "1.0") or 1.0)
    except ValueError:
        interval = 1.0
    return JaxDeviceProbe(interval_s=max(0.01, interval))


def failure_domain_map(graph) -> Dict[int, List[str]]:
    """Device id -> sorted mesh-operator names whose device mesh places
    shards on it, read from the BUILT replicas (empty before the lazy
    mesh construction ran). Non-mesh operators have no entry: their
    failure domain is the host, not a chip."""
    import numpy as np

    out: Dict[int, set] = {}
    for op in getattr(graph, "_ops", []):
        for r in op.replicas:
            mesh = getattr(r, "_mesh", None)
            if mesh is None:
                continue
            for d in np.ravel(mesh.devices):
                out.setdefault(int(d.id), set()).add(op.name)
    return {dev: sorted(names) for dev, names in sorted(out.items())}
