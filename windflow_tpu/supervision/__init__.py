"""Self-healing supervision (no reference analog — the C++ WindFlow
runtime prints the first functor error and ``exit(EXIT_FAILURE)``).

Two planes close the loop between "fault-tolerant" and "self-healing":

- :mod:`supervisor` — graph-level auto-recovery: a supervisor thread
  watches worker deaths and stall-watchdog episodes, tears the runtime
  plane down, restores from the latest committed checkpoint and resumes
  the sources from their recorded positions, under a jittered
  exponential-backoff restart policy with a bounded restart budget
  (:class:`RestartPolicy`). Exactly-once sinks stay duplicate-free
  across restarts via the epoch/generation fencing of
  ``windflow_tpu.sinks.transactional``.
- :mod:`errors` — per-record failure containment: operator-level error
  policies (``FAIL`` default, ``SKIP``, ``RETRY(n, backoff)``,
  ``DEAD_LETTER``) wrap functor invocation on the host path and
  bisect device batches to isolate the offending record on the device
  path; quarantined records land in a :class:`DeadLetterQueue` with
  full exception metadata.
"""

from .errors import DeadLetterQueue, ErrorPolicy
from .health import (DeviceHealthProbe, JaxDeviceProbe, StaticDeviceProbe,
                     failure_domain_map)
from .policy import RestartPolicy
from .supervisor import SupervisionEscalated, Supervisor

__all__ = ["RestartPolicy", "ErrorPolicy", "DeadLetterQueue",
           "Supervisor", "SupervisionEscalated",
           "DeviceHealthProbe", "JaxDeviceProbe", "StaticDeviceProbe",
           "failure_domain_map"]
