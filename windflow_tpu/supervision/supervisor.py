"""Supervisor: graph-level auto-recovery from worker deaths and stalls.

The reference runtime (and this reproduction, pre-supervision) dies with
the first failed functor: ``wait_end`` re-raises the first worker error
and the only recovery path is a human calling ``run(restore_from=...)``.
The supervisor closes the loop with the machinery previous PRs built:

1. **detect** — a worker's error path notifies the supervisor (plus a
   polling sweep that also consumes ``StallWatchdog`` episodes);
2. **back off** — jittered exponential delay under the
   :class:`~windflow_tpu.supervision.policy.RestartPolicy` budget; an
   exhausted budget ESCALATES: the supervisor stands down and
   ``wait_end`` raises the aggregated error;
3. **tear down** — abort pending checkpoint epochs (exactly-once sinks
   learn their staged epochs will never finalize via the coordinator's
   abort listeners), close every channel so blocked producers/consumers
   unwind with ``SupervisorTeardown`` (no EOS cascade — sinks must NOT
   see an end-of-stream marker mid-recovery), and join the old workers
   (a genuinely wedged thread is abandoned: Python threads cannot be
   killed; its next channel touch raises the teardown signal, and
   exactly-once sinks fence its zombie writes);
4. **restore** — rebuild the runtime plane from the stage IR
   (``PipeGraph._rebuild_runtime``, the rescale path) and push a
   COMMITTED checkpoint's blobs back in, walking a FALLBACK LADDER from
   the latest across the retain-K window: a checkpoint that fails
   content verification (``CorruptCheckpointError``) or blows up
   mid-apply is quarantined (``ckpt_N`` -> ``ckpt_N.corrupt``) and the
   next-older one is tried, down to captured-initial full replay as the
   last rung — a corrupt latest checkpoint degrades MTTR, never
   correctness. Restoring epoch N-1 carries ``txn_last_epoch = N-1``,
   so exactly-once sinks abort every pending epoch > N-1 on restore and
   the roll-forward cannot duplicate; sources rewind to the older
   positions with the same blobs. When a device-health probe is wired
   (``with_device_probe`` / ``WF_HEALTH_PROBE``), dead devices are
   excluded from the rebuilt meshes first: mesh ops come back on the
   surviving devices (state relayouts byte-identically), the graph runs
   degraded until the probe sees the device return, then ONE planned
   restart re-expands to full shape;
5. **resume** — fresh workers start; cumulative crash/DLQ counters are
   carried over so dashboards do not zero out after recovery. The
   detect→resume time is the per-event MTTR
   (``Supervision_last_restart_s`` / ``windflow_restart_last_seconds``).

With no committed checkpoint yet, the rebuild restores nothing: source
functors keep their in-memory cursors, so the stream continues from the
crash point (records buffered in the discarded channels are lost — run
with checkpointing for loss-free recovery; supervision enables it
implicitly, the first interval/triggered epoch closes the window).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..basic import WindFlowError
from .policy import RestartPolicy


class SupervisionEscalated(WindFlowError):
    """The restart budget is exhausted (or recovery itself failed): the
    aggregated error of every dead worker, raised by ``wait_end``.
    ``worker_errors`` maps worker name -> exception."""

    def __init__(self, msg: str,
                 worker_errors: Optional[Dict[str, BaseException]] = None
                 ) -> None:
        super().__init__(msg)
        self.worker_errors = dict(worker_errors or {})


class Supervisor(threading.Thread):
    """One per supervised PipeGraph; started by ``PipeGraph.start`` and
    stopped by ``wait_end``. All recovery work runs on this thread."""

    _TICK_S = 0.05

    def __init__(self, graph, policy: Optional[RestartPolicy] = None) -> None:
        super().__init__(name=f"{graph.name}/supervisor", daemon=True)
        self.graph = graph
        self.policy = policy or RestartPolicy.from_env()
        self.active = True  # False once escalated or stopped
        self.escalated: Optional[SupervisionEscalated] = None
        self.restarts = 0
        self.last_restart_s = 0.0  # detect -> resume (MTTR) of the last one
        self.restart_total_s = 0.0
        self.last_cause = ""
        self.abandoned: List[str] = []  # wedged worker threads left behind
        self.history: List[Dict[str, Any]] = []  # bounded, newest last
        # durable-recovery plane: fallback-ladder + device-loss state
        self.last_ladder_depth = 0   # rungs skipped by the last restore
        self.verify_failures = 0     # cumulative corrupt rungs walked past
        self.degraded_devices = 0    # devices currently excluded
        self.planned_restarts = 0    # re-expansion restarts (not failures)
        self._excluded: frozenset = frozenset()
        self._next_probe_t = 0.0
        self._wake = threading.Event()
        self._stop_evt = threading.Event()
        self._stall_seen = 0  # consumed prefix of watchdog.fired
        self._rec = None  # lazy flight-recorder ring ("supervise" track)

    # -- wiring ------------------------------------------------------------
    def note_failure(self, worker) -> None:
        """Worker error-path hook (any thread): wake the loop now."""
        self._wake.set()

    def stop(self) -> None:
        self.active = False
        self._stop_evt.set()
        self._wake.set()

    # -- flight recorder ---------------------------------------------------
    def _span(self, name: str, dur_us: float, arg: Any = None) -> None:
        if self._rec is None:
            g = self.graph
            events = g._stage_flightrec_events_max()
            if events > 0:
                from ..monitoring.flightrec import FlightRecorder
                self._rec = FlightRecorder(
                    events, pid_label="supervise",
                    tid_label=f"{g.name}/supervisor")
                g._recorders.append(self._rec)
        if self._rec is not None:
            try:
                self._rec.event(name, dur_us, arg)
            except Exception:
                pass  # telemetry must never fail a recovery

    # -- the loop ----------------------------------------------------------
    def run(self) -> None:
        while not self._stop_evt.is_set():
            self._wake.wait(self._TICK_S)
            self._wake.clear()
            if self._stop_evt.is_set() or self.graph._ended:
                return
            failed = [w for w in self.graph._workers
                      if w.error is not None]
            stalled = self._new_stalls()
            if failed or stalled:
                try:
                    self._recover(failed, stalled)
                except Exception as e:  # recovery itself failed
                    self._escalate(failed, stalled,
                                   reason=f"recovery failed: "
                                          f"{type(e).__name__}: {e}",
                                   cause=e)
                if not self.active:
                    return
            elif self.active:
                try:
                    self._maybe_reexpand()
                except Exception as e:
                    self._escalate([], [],
                                   reason=f"mesh re-expansion failed: "
                                          f"{type(e).__name__}: {e}",
                                   cause=e)
                    return

    def _new_stalls(self) -> List[str]:
        if not self.policy.restart_on_stall:
            return []
        wd = self.graph._watchdog
        if wd is None:
            return []
        fired = list(wd.fired)
        fresh = fired[self._stall_seen:]
        self._stall_seen = len(fired)
        # only stalls of CURRENT workers trigger recovery (an abandoned
        # zombie flagged again must not restart the healthy new plane)
        live = {w.name for w in self.graph._workers}
        return [n for n in fresh if n in live]

    # -- recovery ----------------------------------------------------------
    def _errors_of(self, failed) -> Dict[str, BaseException]:
        return {w.name: w.error for w in failed if w.error is not None}

    def _escalate(self, failed, stalled, reason: str,
                  cause: Optional[BaseException] = None) -> None:
        errors = self._errors_of(failed)
        parts = [f"{n} ({type(e).__name__}: {e})" for n, e in errors.items()]
        parts += [f"{n} (stalled)" for n in stalled if n not in errors]
        exc = SupervisionEscalated(
            f"supervision gave up after {self.restarts} restart(s): "
            f"{reason}; dead worker(s): {', '.join(parts) or '<none>'}",
            errors)
        if cause is not None:
            exc.__cause__ = cause
        elif errors:
            exc.__cause__ = next(iter(errors.values()))
        self.escalated = exc
        self.active = False
        self._span("supervise:escalate", 0.0, reason)
        # unwind what is left so wait_end's joins return
        self._teardown(join_timeout=5.0)
        self.graph._supervising = False

    def _recover(self, failed, stalled: List[str]) -> None:
        g = self.graph
        t_detect = time.monotonic()
        g._supervising = True  # wait_end spins; the watchdog stands down
        errors = self._errors_of(failed)
        cause = "; ".join(
            [f"{n}: {type(e).__name__}: {e}" for n, e in errors.items()]
            + [f"{n}: stalled" for n in stalled])
        self.last_cause = cause
        self._span("supervise:failure", 0.0, cause)
        if not self.policy.allow_restart():
            self._escalate(
                failed, stalled,
                reason=f"restart budget exhausted "
                       f"({self.policy.max_restarts} per "
                       f"{self.policy.window_s:.0f}s window)")
            return
        delay = self.policy.next_backoff()
        self.policy.note_restart()
        self._span("supervise:backoff", delay * 1e6,
                   {"attempt": self.restarts + 1})
        if self._stop_evt.wait(delay):
            g._supervising = False
            return
        t0 = time.monotonic()
        self._teardown()
        self._span("supervise:teardown", (time.monotonic() - t0) * 1e6)
        t0 = time.monotonic()
        cid = self._rebuild_and_restore()
        self._span("supervise:restore", (time.monotonic() - t0) * 1e6,
                   {"ckpt_id": cid})
        for w in g._workers:
            w.start()
        mttr = time.monotonic() - t_detect
        self.restarts += 1
        self.last_restart_s = mttr
        self.restart_total_s += mttr
        self.history.append({
            "t_unix": time.time(), "cause": cause, "ckpt_id": cid,
            "mttr_s": round(mttr, 6), "backoff_s": round(delay, 6),
            "abandoned": [n for n in stalled]})
        del self.history[:-64]
        g._supervising = False
        self._span("supervise:resume", mttr * 1e6,
                   {"restart": self.restarts, "ckpt_id": cid})

    def _teardown(self, join_timeout: float = 10.0) -> None:
        """Unwind the old runtime plane without an EOS cascade."""
        g = self.graph
        coord = g._coordinator
        if coord is not None:
            # epochs opened against the dying plane can never complete;
            # exactly-once sinks are notified their staged epochs will
            # not finalize (they roll forward/abort on restore instead)
            coord.abort_pending()
        for s in g._stages:
            for ch in s.channels:
                ch.close()
        old = list(g._workers)
        for w in old:
            if w is not threading.current_thread():
                w.join(timeout=join_timeout)
        wedged = [w.name for w in old if w.is_alive()]
        if wedged:
            # cannot kill a Python thread: abandon it. Its next channel
            # touch raises SupervisorTeardown; EO-sink zombies are fenced.
            self.abandoned.extend(wedged)
            self._span("supervise:abandon", 0.0, wedged)

    def _rebuild_and_restore(self) -> Optional[int]:
        """Rebuild the runtime plane and push a committed checkpoint
        back in, walking the fallback ladder newest -> oldest when a
        rung fails verification or mid-apply. Returns the restored
        checkpoint id (None for the full-replay rung)."""
        g = self.graph
        coord = g._coordinator
        carry = self._collect_carryover()
        # device health first: the rebuilt meshes must avoid dead chips
        self._apply_device_exclusions()
        g._rebuild_runtime()
        cid = None
        if coord is not None:
            cid = self._restore_ladder(coord)
            coord.expected_acks = len(g._workers)
            coord.worker_names = [w.name for w in g._workers]
        self._apply_carryover(carry)
        return cid

    def _restore_ladder(self, coord) -> Optional[int]:
        """Walk committed checkpoints newest -> oldest until one both
        verifies and applies. A failing rung is quarantined
        (``ckpt_N.corrupt`` — kept for post-mortem, invisible to
        restore) and the partially-applied plane is rebuilt clean before
        the next rung. Exhausting the ladder falls back to
        captured-initial full replay: exactly-once sinks abort every
        pre-committed epoch on the way down (the restored
        ``txn_last_epoch`` / the full-replay reset), so no rung can
        duplicate records."""
        g = self.graph
        store = coord.store
        depth = 0
        for cid in reversed(store.completed_ids()):
            try:
                ckpt_dir = store._dirname(cid)
                manifest = store.load_manifest(ckpt_dir)
                states = store.load_states(ckpt_dir, manifest)
                # epoch ids roll back to the restored rung BEFORE the
                # rebuild, exactly like restore_from=: re-created
                # sources anchor their injection cursor here, so a
                # replayed barrier re-uses the old epoch id and the
                # exactly-once sinks' idempotent commit discards it —
                # this is what keeps a ladder rung below the pre-crash
                # latest from duplicating the already-committed epochs
                with coord._lock:
                    coord._alloc_id = cid
                    coord.requested_id = cid
                    coord.last_completed_id = cid
                g._rebuild_runtime()
                g._restore_states(states)
            except Exception as e:
                # CorruptCheckpointError from verification, or any
                # mid-apply explosion: this rung is unusable. The dirty
                # plane (if apply got that far) is discarded by the next
                # rung's / the full-replay rung's rebuild.
                depth += 1
                self.verify_failures += 1
                self._span("recover:verify", 0.0, {
                    "ckpt_id": cid,
                    "error": f"{type(e).__name__}: {e}"})
                quarantined = store.quarantine(cid)
                self._span("recover:fallback", 0.0, {
                    "ckpt_id": cid, "quarantined": quarantined,
                    "next": "older checkpoint"})
                continue
            self.last_ladder_depth = depth
            return cid
        # no (usable) checkpoint: resuming from the sources' in-memory
        # cursors would silently drop every record that sat in the
        # discarded channels — reset replayable sources to their
        # captured INITIAL positions instead (full replay from epoch 0;
        # exactly-once sinks discard replayed epochs that already
        # committed and abort stale pre-committed ones, so the replay
        # is duplicate-free)
        with coord._lock:
            coord._alloc_id = 0
            coord.requested_id = 0
            coord.last_completed_id = 0
        g._rebuild_runtime()
        self._reset_sources_to_initial()
        if depth:
            self._span("recover:fallback", 0.0, {
                "ckpt_id": None, "next": "full replay",
                "rungs_failed": depth})
        self.last_ladder_depth = depth
        return None

    # -- device-loss failover (supervision/health.py) ----------------------
    def _apply_device_exclusions(self) -> None:
        """Consult the graph's device-health probe (when wired) and
        publish dead devices into the mesh-core exclusion registry, so
        the rebuild lands mesh state on surviving devices only. Runs
        BEFORE ``_rebuild_runtime``. A probe exception keeps the
        previous exclusion set — no new information must never block a
        recovery."""
        g = self.graph
        probe = getattr(g, "_device_probe", None)
        if probe is None:
            return
        try:
            dead = frozenset(int(d) for d in probe.dead_devices())
        except Exception:
            dead = self._excluded
        from ..mesh.core import set_excluded_devices
        if dead != self._excluded:
            set_excluded_devices(dead)
            if dead:
                try:
                    from .health import failure_domain_map
                    domains = {d: failure_domain_map(g).get(d, [])
                               for d in sorted(dead)}
                except Exception:
                    domains = {}
                self._span("mesh:degrade", 0.0, {
                    "excluded": sorted(dead), "domains": domains})
            self._excluded = dead
        self.degraded_devices = len(dead)

    def _maybe_reexpand(self) -> None:
        """While degraded, poll the probe at its own pace; the moment an
        excluded device reports healthy again, perform ONE planned
        restart so the mesh re-expands to full shape (the rebuild pulls
        the shrunken exclusion set through ``_apply_device_exclusions``
        and the relayout restore does the rest)."""
        g = self.graph
        probe = getattr(g, "_device_probe", None)
        if probe is None or not self._excluded or g._ended:
            return
        if all(not w.is_alive() for w in g._workers):
            return  # the stream is finishing; nothing to re-expand for
        now = time.monotonic()
        if now < self._next_probe_t:
            return
        self._next_probe_t = now + max(
            0.01, float(getattr(probe, "interval_s", 1.0) or 1.0))
        try:
            dead = frozenset(int(d) for d in probe.dead_devices())
        except Exception:
            return
        recovered = sorted(self._excluded - dead)
        if not recovered:
            return
        self._planned_restart(
            f"mesh re-expansion: device(s) {recovered} recovered")

    def _planned_restart(self, cause: str) -> None:
        """A deliberate restart (re-expansion): same teardown/rebuild/
        restore flow as ``_recover`` but no backoff and no restart-budget
        consumption — recovering capacity must never eat the failure
        budget."""
        g = self.graph
        t0 = time.monotonic()
        g._supervising = True
        try:
            self.last_cause = cause
            self._span("supervise:planned", 0.0, cause)
            self._teardown()
            cid = self._rebuild_and_restore()
            for w in g._workers:
                w.start()
            mttr = time.monotonic() - t0
            self.planned_restarts += 1
            self.last_restart_s = mttr
            self.restart_total_s += mttr
            self.history.append({
                "t_unix": time.time(), "cause": cause, "ckpt_id": cid,
                "mttr_s": round(mttr, 6), "planned": True})
            del self.history[:-64]
            self._span("supervise:resume", mttr * 1e6,
                       {"planned": True, "ckpt_id": cid})
        finally:
            g._supervising = False

    def _reset_sources_to_initial(self) -> None:
        initial = getattr(self.graph, "_initial_positions", None) or {}
        for op in self.graph._ops:
            for r in op.replicas:
                pos = initial.get((op.name, r.idx))
                if pos is not None:
                    r._restore_position = pos
                    r.stats.inputs_received = 0  # the stream restarts
                # exactly-once sinks: the dead generation may have left
                # pre-committed (.pending / prepared) epochs that no
                # checkpoint ever finalized. The stream restarts from
                # ZERO, so the replay regenerates their records — they
                # must ABORT now; a later checkpointed restore would
                # otherwise roll them forward and DUPLICATE records
                # (caught by the double-crash chaos differential)
                drv = getattr(r, "_txn", None)
                if drv is not None:
                    drv.restore({"txn_last_epoch": 0})

    # -- cumulative-counter carryover (dashboards must not zero out) -------
    # NOT here: shed_records/shed_bytes. They ride the SOURCE's
    # checkpoint snapshot instead (SourceReplica.snapshot_state), which
    # keeps them aligned with the rewound replay cursor — additive
    # carryover on top would double-count every shed in the replayed
    # segment (offered == admitted + shed must hold exactly across a
    # restart).
    _CARRY_FIELDS = ("worker_crashes", "dlq_records", "dlq_skipped",
                     "dlq_retries", "kafka_reconnects")

    def _collect_carryover(self) -> Dict[Any, Dict[str, Any]]:
        out: Dict[Any, Dict[str, Any]] = {}
        for op in self.graph._ops:
            for r in {id(r): r for r in op.replicas}.values():
                ent = {f: getattr(r.stats, f, 0)
                       for f in self._CARRY_FIELDS}
                ent["worker_last_error"] = r.stats.worker_last_error
                out[(r.stats.op_name, r.idx)] = ent
        return out

    def _apply_carryover(self, carry: Dict[Any, Dict[str, Any]]) -> None:
        for op in self.graph._ops:
            for r in {id(r): r for r in op.replicas}.values():
                ent = carry.get((r.stats.op_name, r.idx))
                if not ent:
                    continue
                for f in self._CARRY_FIELDS:
                    setattr(r.stats, f,
                            getattr(r.stats, f, 0) + ent.get(f, 0))
                if ent.get("worker_last_error"):
                    r.stats.worker_last_error = ent["worker_last_error"]

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "Supervision_restarts": self.restarts,
            "Supervision_last_restart_s": round(self.last_restart_s, 6),
            "Supervision_restart_total_s": round(self.restart_total_s, 6),
            "Supervision_last_cause": self.last_cause,
            "Supervision_escalated": self.escalated is not None,
            "Supervision_abandoned_threads": list(self.abandoned),
            "Supervision_budget_remaining": max(
                0, self.policy.max_restarts - self.policy.consecutive),
            "Supervision_planned_restarts": self.planned_restarts,
            "Recovery_ladder_depth": self.last_ladder_depth,
            "Recovery_verify_failures": self.verify_failures,
            "Recovery_degraded_devices": self.degraded_devices,
            "Supervision_history": list(self.history),
        }
