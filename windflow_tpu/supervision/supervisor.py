"""Supervisor: graph-level auto-recovery from worker deaths and stalls.

The reference runtime (and this reproduction, pre-supervision) dies with
the first failed functor: ``wait_end`` re-raises the first worker error
and the only recovery path is a human calling ``run(restore_from=...)``.
The supervisor closes the loop with the machinery previous PRs built:

1. **detect** — a worker's error path notifies the supervisor (plus a
   polling sweep that also consumes ``StallWatchdog`` episodes);
2. **back off** — jittered exponential delay under the
   :class:`~windflow_tpu.supervision.policy.RestartPolicy` budget; an
   exhausted budget ESCALATES: the supervisor stands down and
   ``wait_end`` raises the aggregated error;
3. **tear down** — abort pending checkpoint epochs (exactly-once sinks
   learn their staged epochs will never finalize via the coordinator's
   abort listeners), close every channel so blocked producers/consumers
   unwind with ``SupervisorTeardown`` (no EOS cascade — sinks must NOT
   see an end-of-stream marker mid-recovery), and join the old workers
   (a genuinely wedged thread is abandoned: Python threads cannot be
   killed; its next channel touch raises the teardown signal, and
   exactly-once sinks fence its zombie writes);
4. **restore** — rebuild the runtime plane from the stage IR
   (``PipeGraph._rebuild_runtime``, the rescale path) and push the
   latest COMMITTED checkpoint's blobs back in: sources resume from
   their recorded positions, exactly-once sinks roll staged epochs
   forward/abort per the 2PC recovery contract — restarts are
   duplicate-free out of the box;
5. **resume** — fresh workers start; cumulative crash/DLQ counters are
   carried over so dashboards do not zero out after recovery. The
   detect→resume time is the per-event MTTR
   (``Supervision_last_restart_s`` / ``windflow_restart_last_seconds``).

With no committed checkpoint yet, the rebuild restores nothing: source
functors keep their in-memory cursors, so the stream continues from the
crash point (records buffered in the discarded channels are lost — run
with checkpointing for loss-free recovery; supervision enables it
implicitly, the first interval/triggered epoch closes the window).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..basic import WindFlowError
from .policy import RestartPolicy


class SupervisionEscalated(WindFlowError):
    """The restart budget is exhausted (or recovery itself failed): the
    aggregated error of every dead worker, raised by ``wait_end``.
    ``worker_errors`` maps worker name -> exception."""

    def __init__(self, msg: str,
                 worker_errors: Optional[Dict[str, BaseException]] = None
                 ) -> None:
        super().__init__(msg)
        self.worker_errors = dict(worker_errors or {})


class Supervisor(threading.Thread):
    """One per supervised PipeGraph; started by ``PipeGraph.start`` and
    stopped by ``wait_end``. All recovery work runs on this thread."""

    _TICK_S = 0.05

    def __init__(self, graph, policy: Optional[RestartPolicy] = None) -> None:
        super().__init__(name=f"{graph.name}/supervisor", daemon=True)
        self.graph = graph
        self.policy = policy or RestartPolicy.from_env()
        self.active = True  # False once escalated or stopped
        self.escalated: Optional[SupervisionEscalated] = None
        self.restarts = 0
        self.last_restart_s = 0.0  # detect -> resume (MTTR) of the last one
        self.restart_total_s = 0.0
        self.last_cause = ""
        self.abandoned: List[str] = []  # wedged worker threads left behind
        self.history: List[Dict[str, Any]] = []  # bounded, newest last
        self._wake = threading.Event()
        self._stop_evt = threading.Event()
        self._stall_seen = 0  # consumed prefix of watchdog.fired
        self._rec = None  # lazy flight-recorder ring ("supervise" track)

    # -- wiring ------------------------------------------------------------
    def note_failure(self, worker) -> None:
        """Worker error-path hook (any thread): wake the loop now."""
        self._wake.set()

    def stop(self) -> None:
        self.active = False
        self._stop_evt.set()
        self._wake.set()

    # -- flight recorder ---------------------------------------------------
    def _span(self, name: str, dur_us: float, arg: Any = None) -> None:
        if self._rec is None:
            g = self.graph
            events = g._stage_flightrec_events_max()
            if events > 0:
                from ..monitoring.flightrec import FlightRecorder
                self._rec = FlightRecorder(
                    events, pid_label="supervise",
                    tid_label=f"{g.name}/supervisor")
                g._recorders.append(self._rec)
        if self._rec is not None:
            try:
                self._rec.event(name, dur_us, arg)
            except Exception:
                pass  # telemetry must never fail a recovery

    # -- the loop ----------------------------------------------------------
    def run(self) -> None:
        while not self._stop_evt.is_set():
            self._wake.wait(self._TICK_S)
            self._wake.clear()
            if self._stop_evt.is_set() or self.graph._ended:
                return
            failed = [w for w in self.graph._workers
                      if w.error is not None]
            stalled = self._new_stalls()
            if failed or stalled:
                try:
                    self._recover(failed, stalled)
                except Exception as e:  # recovery itself failed
                    self._escalate(failed, stalled,
                                   reason=f"recovery failed: "
                                          f"{type(e).__name__}: {e}",
                                   cause=e)
                if not self.active:
                    return

    def _new_stalls(self) -> List[str]:
        if not self.policy.restart_on_stall:
            return []
        wd = self.graph._watchdog
        if wd is None:
            return []
        fired = list(wd.fired)
        fresh = fired[self._stall_seen:]
        self._stall_seen = len(fired)
        # only stalls of CURRENT workers trigger recovery (an abandoned
        # zombie flagged again must not restart the healthy new plane)
        live = {w.name for w in self.graph._workers}
        return [n for n in fresh if n in live]

    # -- recovery ----------------------------------------------------------
    def _errors_of(self, failed) -> Dict[str, BaseException]:
        return {w.name: w.error for w in failed if w.error is not None}

    def _escalate(self, failed, stalled, reason: str,
                  cause: Optional[BaseException] = None) -> None:
        errors = self._errors_of(failed)
        parts = [f"{n} ({type(e).__name__}: {e})" for n, e in errors.items()]
        parts += [f"{n} (stalled)" for n in stalled if n not in errors]
        exc = SupervisionEscalated(
            f"supervision gave up after {self.restarts} restart(s): "
            f"{reason}; dead worker(s): {', '.join(parts) or '<none>'}",
            errors)
        if cause is not None:
            exc.__cause__ = cause
        elif errors:
            exc.__cause__ = next(iter(errors.values()))
        self.escalated = exc
        self.active = False
        self._span("supervise:escalate", 0.0, reason)
        # unwind what is left so wait_end's joins return
        self._teardown(join_timeout=5.0)
        self.graph._supervising = False

    def _recover(self, failed, stalled: List[str]) -> None:
        g = self.graph
        t_detect = time.monotonic()
        g._supervising = True  # wait_end spins; the watchdog stands down
        errors = self._errors_of(failed)
        cause = "; ".join(
            [f"{n}: {type(e).__name__}: {e}" for n, e in errors.items()]
            + [f"{n}: stalled" for n in stalled])
        self.last_cause = cause
        self._span("supervise:failure", 0.0, cause)
        if not self.policy.allow_restart():
            self._escalate(
                failed, stalled,
                reason=f"restart budget exhausted "
                       f"({self.policy.max_restarts} per "
                       f"{self.policy.window_s:.0f}s window)")
            return
        delay = self.policy.next_backoff()
        self.policy.note_restart()
        self._span("supervise:backoff", delay * 1e6,
                   {"attempt": self.restarts + 1})
        if self._stop_evt.wait(delay):
            g._supervising = False
            return
        t0 = time.monotonic()
        self._teardown()
        self._span("supervise:teardown", (time.monotonic() - t0) * 1e6)
        t0 = time.monotonic()
        cid = self._rebuild_and_restore()
        self._span("supervise:restore", (time.monotonic() - t0) * 1e6,
                   {"ckpt_id": cid})
        for w in g._workers:
            w.start()
        mttr = time.monotonic() - t_detect
        self.restarts += 1
        self.last_restart_s = mttr
        self.restart_total_s += mttr
        self.history.append({
            "t_unix": time.time(), "cause": cause, "ckpt_id": cid,
            "mttr_s": round(mttr, 6), "backoff_s": round(delay, 6),
            "abandoned": [n for n in stalled]})
        del self.history[:-64]
        g._supervising = False
        self._span("supervise:resume", mttr * 1e6,
                   {"restart": self.restarts, "ckpt_id": cid})

    def _teardown(self, join_timeout: float = 10.0) -> None:
        """Unwind the old runtime plane without an EOS cascade."""
        g = self.graph
        coord = g._coordinator
        if coord is not None:
            # epochs opened against the dying plane can never complete;
            # exactly-once sinks are notified their staged epochs will
            # not finalize (they roll forward/abort on restore instead)
            coord.abort_pending()
        for s in g._stages:
            for ch in s.channels:
                ch.close()
        old = list(g._workers)
        for w in old:
            if w is not threading.current_thread():
                w.join(timeout=join_timeout)
        wedged = [w.name for w in old if w.is_alive()]
        if wedged:
            # cannot kill a Python thread: abandon it. Its next channel
            # touch raises SupervisorTeardown; EO-sink zombies are fenced.
            self.abandoned.extend(wedged)
            self._span("supervise:abandon", 0.0, wedged)

    def _rebuild_and_restore(self) -> Optional[int]:
        """Rebuild the runtime plane and push the latest committed
        checkpoint back in. Returns the restored checkpoint id (None
        when no checkpoint has committed yet)."""
        g = self.graph
        coord = g._coordinator
        carry = self._collect_carryover()
        g._rebuild_runtime()
        cid = None
        if coord is not None:
            cid = coord.store.latest()
            if cid is None:
                # no checkpoint has COMMITTED yet: resuming from the
                # sources' in-memory cursors would silently drop every
                # record that sat in the discarded channels — reset
                # replayable sources to their captured INITIAL positions
                # instead (full replay; exactly-once sinks have
                # committed nothing, so the replay is duplicate-free)
                self._reset_sources_to_initial()
            else:
                ckpt_dir = coord.store._dirname(cid)
                manifest = coord.store.load_manifest(ckpt_dir)
                g._restore_states(
                    coord.store.load_states(ckpt_dir, manifest))
                # new epochs continue after the restored one; rebuilt
                # sources anchor their barrier cursor to requested_id
                # at Worker construction, which _rebuild_runtime already
                # ran — keep the ids monotone for the next trigger
                with coord._lock:
                    coord._alloc_id = max(coord._alloc_id, cid)
                    if coord.requested_id < cid:
                        coord.requested_id = cid
                    coord.last_completed_id = max(
                        coord.last_completed_id, cid)
            coord.expected_acks = len(g._workers)
            coord.worker_names = [w.name for w in g._workers]
        self._apply_carryover(carry)
        return cid

    def _reset_sources_to_initial(self) -> None:
        initial = getattr(self.graph, "_initial_positions", None) or {}
        for op in self.graph._ops:
            for r in op.replicas:
                pos = initial.get((op.name, r.idx))
                if pos is not None:
                    r._restore_position = pos
                    r.stats.inputs_received = 0  # the stream restarts
                # exactly-once sinks: the dead generation may have left
                # pre-committed (.pending / prepared) epochs that no
                # checkpoint ever finalized. The stream restarts from
                # ZERO, so the replay regenerates their records — they
                # must ABORT now; a later checkpointed restore would
                # otherwise roll them forward and DUPLICATE records
                # (caught by the double-crash chaos differential)
                drv = getattr(r, "_txn", None)
                if drv is not None:
                    drv.restore({"txn_last_epoch": 0})

    # -- cumulative-counter carryover (dashboards must not zero out) -------
    # NOT here: shed_records/shed_bytes. They ride the SOURCE's
    # checkpoint snapshot instead (SourceReplica.snapshot_state), which
    # keeps them aligned with the rewound replay cursor — additive
    # carryover on top would double-count every shed in the replayed
    # segment (offered == admitted + shed must hold exactly across a
    # restart).
    _CARRY_FIELDS = ("worker_crashes", "dlq_records", "dlq_skipped",
                     "dlq_retries", "kafka_reconnects")

    def _collect_carryover(self) -> Dict[Any, Dict[str, Any]]:
        out: Dict[Any, Dict[str, Any]] = {}
        for op in self.graph._ops:
            for r in {id(r): r for r in op.replicas}.values():
                ent = {f: getattr(r.stats, f, 0)
                       for f in self._CARRY_FIELDS}
                ent["worker_last_error"] = r.stats.worker_last_error
                out[(r.stats.op_name, r.idx)] = ent
        return out

    def _apply_carryover(self, carry: Dict[Any, Dict[str, Any]]) -> None:
        for op in self.graph._ops:
            for r in {id(r): r for r in op.replicas}.values():
                ent = carry.get((r.stats.op_name, r.idx))
                if not ent:
                    continue
                for f in self._CARRY_FIELDS:
                    setattr(r.stats, f,
                            getattr(r.stats, f, 0) + ent.get(f, 0))
                if ent.get("worker_last_error"):
                    r.stats.worker_last_error = ent["worker_last_error"]

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "Supervision_restarts": self.restarts,
            "Supervision_last_restart_s": round(self.last_restart_s, 6),
            "Supervision_restart_total_s": round(self.restart_total_s, 6),
            "Supervision_last_cause": self.last_cause,
            "Supervision_escalated": self.escalated is not None,
            "Supervision_abandoned_threads": list(self.abandoned),
            "Supervision_budget_remaining": max(
                0, self.policy.max_restarts - self.policy.consecutive),
            "Supervision_history": list(self.history),
        }
